"""Structured-output + parallel-sampling A/B micro-bench on the
serving engine.

Two arms, both on the SAME engine (one decode compile covers free and
constrained traffic — the mask rides the existing trace, and this tool
pins that):

- **constrained vs free**: the same seeded decode workload run free,
  then under a regex grammar (`serving/structured.py`). The grammar
  seam is a per-slot [vocab] bitmask applied inside the one compiled
  decode step; the HOST cost is the FSM walk plus a mask upload ONLY
  on state change (`mask_uploads` counter — the A/B seam, like
  prefill_forward_tokens was for the prefix cache). Every constrained
  completion must replay FSM-legal and parse (the tool asserts both).
- **n=1 x 4 vs n=4**: four serial submits of one prompt vs ONE
  fan-out submit (`n=4`). The fan-out arm prefills the prompt once and
  COW-aliases its blocks into the other three decode slots
  (`prefill_tokens_saved` / `prefix_hits` are the seam); every sample
  must be token-exact vs its serially-seeded n=1 twin — fan-out is a
  scheduling change, not a semantics change.

On CPU the wall-clock is a harness smoke; ON CHIP mask-upload counts,
prefill tokens removed, and the tok/s ratios transfer directly.

Emits ONE BENCH-style JSON record on stdout (and to --out), like the
other bench tools; runs in the bench.py extras chain and the
bench_serving_queue one-window runner.

  python tools/bench_structured.py [--smoke] [--requests N] [--new N]
                                   [--slots N] [--out FILE]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform

# bounded grammar over the identity token table (token i <-> chr(i)):
# digits only, 2-6 chars — enough FSM states that masks actually
# change per step, small enough that every budget covers max_path_len
GRAMMAR = {"type": "regex", "pattern": "[0-9]{2,6}"}


def _build(args):
    import jax
    import numpy as np

    from megatron_tpu.config import ModelConfig, ServingConfig
    from megatron_tpu.inference.generation import Generator
    from megatron_tpu.models import language_model as lm
    from megatron_tpu.serving import ServingEngine

    cfg = ModelConfig(
        num_layers=args.layers, hidden_size=args.hidden,
        num_attention_heads=args.heads,
        num_kv_heads=max(args.heads // 2, 1), vocab_size=args.vocab,
        seq_length=args.seq, max_position_embeddings=args.seq,
        make_vocab_size_divisible_by=64,
        compute_dtype="bfloat16").derived()
    params = lm.model_init(jax.random.PRNGKey(0), cfg)
    # eos_id=-1: no early EOS — free rows decode exactly --new tokens,
    # so the constrained-vs-free arms measure comparable volumes
    gen = Generator(params, cfg, eos_id=-1, pad_id=0)
    # block pool + prefix cache: the COW fan-out arm's alias seam
    serving = ServingConfig(num_slots=args.slots,
                            max_queue=max(4 * args.requests, 64),
                            kv_block_size=16,
                            enable_prefix_cache=True,
                            speculative_k=args.speculative_k)
    eng = ServingEngine(gen, serving.validate(cfg))
    rs = np.random.RandomState(0)
    prompts = [rs.randint(1, args.vocab, args.prompt).tolist()
               for _ in range(args.requests)]
    return eng, prompts


def _drain(eng, reqs):
    return [r.result(timeout=600)[0] for r in reqs]


def _arm_constrained_vs_free(eng, prompts, args) -> dict:
    from megatron_tpu.serving import SamplingOptions
    from megatron_tpu.serving.structured import compile_response_format
    sampling = SamplingOptions(temperature=0.0)
    fsm = compile_response_format(GRAMMAR, args.vocab)
    budget = max(args.new, fsm.max_path_len)

    def run(response_format):
        snap0 = eng.metrics.snapshot()
        t0 = time.monotonic()
        reqs = [eng.submit(p, budget, sampling, seed=i,
                           response_format=response_format)
                for i, p in enumerate(prompts)]
        outs = _drain(eng, reqs)
        wall = time.monotonic() - t0
        snap = eng.metrics.snapshot()
        d = {k: int(snap[k] - snap0[k])
             for k in ("tokens_generated", "decode_steps",
                       "mask_uploads", "structured_requests",
                       "grammar_dead_ends")}
        toks = [o[len(p):] for o, p in zip(outs, prompts)]
        return d, toks, wall

    free_d, _, free_wall = run(None)
    con_d, con_toks, con_wall = run(GRAMMAR)
    # validity is the point of the subsystem: every constrained stream
    # must replay FSM-legal AND parse (bounded grammar, covered budget)
    for t in con_toks:
        legal, _ = fsm.replay(t)
        assert legal, f"constrained stream is not FSM-legal: {t}"
        assert fsm.final_text_valid(t), \
            f"constrained output does not parse: {t}"
    # the mask-upload cadence seam: uploads track FSM state CHANGES
    # (at most one per slot-activation + one per committed token),
    # never one per decode step per slot
    transitions = sum(len(t) for t in con_toks) + len(con_toks)
    assert 0 < con_d["mask_uploads"] <= transitions, con_d
    assert free_d["mask_uploads"] == 0, free_d
    return {
        "grammar": GRAMMAR["pattern"],
        "free": {**free_d, "wall_s": round(free_wall, 3),
                 "tok_s": round(free_d["tokens_generated"]
                                / max(free_wall, 1e-9), 1)},
        "constrained": {**con_d, "wall_s": round(con_wall, 3),
                        "tok_s": round(con_d["tokens_generated"]
                                       / max(con_wall, 1e-9), 1)},
        "outputs_parse": True,  # the asserts above
        "constrained_overhead_x": round(
            max(con_wall, 1e-9) / max(free_wall, 1e-9), 2),
    }


def _arm_fanout(eng, prompts, args) -> dict:
    from megatron_tpu.serving import SamplingOptions
    sampling = SamplingOptions(temperature=0.8, top_k=8)
    n = min(4, args.slots)
    prompt = prompts[0]

    def counters(snap0, snap):
        return {k: int(snap[k] - snap0[k])
                for k in ("prefill_forward_tokens",
                          "prefill_tokens_saved", "prefix_hits",
                          "fanout_requests", "fanout_samples")}

    # serial arm: n independent n=1 submits, seeds seed+i — the exact
    # streams the fan-out arm must reproduce. Sequential on purpose:
    # concurrent serial submits would share the prefix cache and blur
    # the prefill-savings A/B.
    snap0 = eng.metrics.snapshot()
    t0 = time.monotonic()
    serial_out = []
    for i in range(n):
        r = eng.submit(prompt, args.new, sampling, seed=7 + i)
        serial_out.append(r.result(timeout=600)[0])
    serial_wall = time.monotonic() - t0
    serial_d = counters(snap0, eng.metrics.snapshot())

    snap0 = eng.metrics.snapshot()
    t0 = time.monotonic()
    agg = eng.submit(prompt, args.new, sampling, seed=7, n=n, best_of=n)
    toks_list, _ = agg.result(timeout=600)
    fan_wall = time.monotonic() - t0
    fan_d = counters(snap0, eng.metrics.snapshot())

    # semantics: each sample token-exact vs its serially-seeded twin
    # (result() orders best-first; children are sample-index ordered)
    got = [list(c.prompt) + list(c.generated) for c in agg.children]
    assert got == serial_out, (
        "fan-out samples diverged from serial n=1 submissions — "
        f"{got} vs {serial_out}")
    assert sorted(map(tuple, toks_list)) == sorted(map(tuple, got))
    # the COW seam: ONE real prefill for n samples — every other
    # sample aliases the leader's blocks (block-aligned savings)
    assert fan_d["fanout_requests"] == 1 and fan_d["fanout_samples"] == n
    assert fan_d["prefill_tokens_saved"] > 0, fan_d
    assert fan_d["prefill_forward_tokens"] < n * len(prompt), fan_d
    return {
        "n": n,
        "serial": {**serial_d, "wall_s": round(serial_wall, 3)},
        "fanout": {**fan_d, "wall_s": round(fan_wall, 3)},
        "samples_token_exact": True,  # the asserts above
        "prefill_reduction_x": round(
            max(serial_d["prefill_forward_tokens"], 1)
            / max(fan_d["prefill_forward_tokens"], 1), 2),
        "fanout_speedup_x": round(
            max(serial_wall, 1e-9) / max(fan_wall, 1e-9), 2),
    }


def main(argv=None):
    ensure_env_platform()
    p = argparse.ArgumentParser("bench_structured", description=__doc__)
    p.add_argument("--out", default="/tmp/bench_structured.log")
    p.add_argument("--smoke", action="store_true",
                   help="tiny shapes for the CPU harness tier")
    p.add_argument("--requests", type=int, default=8)
    # NOT a multiple of the 16-token block: a whole-prompt prefix hit
    # caps at plen-1, so a block-aligned prompt would round the COW
    # alias down to zero blocks and hide the fan-out savings
    p.add_argument("--prompt", type=int, default=24)
    p.add_argument("--new", type=int, default=24)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--speculative_k", type=int, default=0,
                   help="compose the grammar gate with self-drafting "
                        "(draft tokens violating the FSM fail verify)")
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--seq", type=int, default=256)
    args = p.parse_args(argv)
    if args.smoke:
        args.requests = min(args.requests, 4)
        args.new = min(args.new, 10)
        args.hidden, args.vocab, args.seq = 64, 128, 128

    import jax
    eng, prompts = _build(args)
    try:
        # warmup compiles prefill + decode (and verify when spec-k on)
        from megatron_tpu.serving import SamplingOptions
        eng.generate(prompts[0][:8], 2, SamplingOptions(temperature=0.0),
                     seed=0)
        constrained = _arm_constrained_vs_free(eng, prompts, args)
        fanout = _arm_fanout(eng, prompts, args)
        # ZERO new traces: free + constrained + fan-out all rode the
        # same compiled decode step (the tentpole's compile contract)
        decode_traces = int(getattr(eng, "_decode_traces", 1))
        assert decode_traces == 1, \
            f"grammar/fan-out traffic recompiled decode: {decode_traces}"
    finally:
        eng.close()

    dev = jax.devices()[0]
    record = {
        "bench": "structured_nbest",
        "device": getattr(dev, "device_kind", dev.platform),
        "requests": args.requests,
        "new_tokens": args.new,
        "speculative_k": args.speculative_k,
        "decode_compiles": 1,
        "constrained_vs_free": constrained,
        "n1_vs_n4": fanout,
    }
    line = json.dumps(record)
    print(line, flush=True)
    with open(args.out, "w") as f:
        f.write(line + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
