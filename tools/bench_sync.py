"""Host-sync cadence + dispatch-gap micro-bench.

The async-dispatch layer (training/loop.py metrics window, serving
decode_sync_interval) exists to take host round-trips off the device's
critical path. This tool measures exactly that, before/after style:

- TRAINING arm: the same tiny train run twice — --sync_metrics
  semantics (fetch every step) vs the async window — counting host
  syncs through the loop's `_device_fetch` seam and timing steady-state
  ms/step. On CPU the times are only a harness smoke (the cpu backend
  keeps a one-step dispatch barrier — see loop.py overlap_dispatch);
  ON CHIP the delta between the two arms IS the dispatch gap the
  per-step fetch was costing.
- SERVING arm: the continuous-batching engine at decode_sync_interval
  1 vs K on the same seeded burst — host syncs/token (must be 1/K) and
  aggregate tok/s.

Emits ONE BENCH-style JSON record on stdout (and to --out), like the
other bench tools; runs in the bench.py extras chain.

  python tools/bench_sync.py [--iters N] [--log_interval N]
                             [--requests N] [--new N] [--sync_k K]
                             [--out FILE]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform


def _bench_training(args) -> dict:
    import dataclasses

    import jax
    import numpy as np

    from megatron_tpu.config import (DataConfig, MegatronConfig,
                                     ModelConfig, OptimizerConfig,
                                     TrainingConfig)
    from megatron_tpu.training import loop as loop_mod

    model = ModelConfig(
        num_layers=args.layers, hidden_size=args.hidden,
        num_attention_heads=args.heads, vocab_size=args.vocab,
        seq_length=args.seq, compute_dtype="bfloat16").derived()

    def cfg_for(sync: bool) -> MegatronConfig:
        return MegatronConfig(
            model=model,
            optimizer=OptimizerConfig(lr=1e-4),
            training=TrainingConfig(
                micro_batch_size=args.micro_batch,
                global_batch_size=args.micro_batch * 2,
                train_iters=args.iters, log_interval=args.log_interval,
                sync_metrics=sync),
            data=DataConfig(num_workers=0),
        ).validate(n_devices=1)

    rs = np.random.RandomState(0)

    def batches():
        while True:
            yield {"tokens": rs.randint(
                0, args.vocab,
                (2, args.micro_batch, args.seq + 1)).astype(np.int32),
                "loss_mask": np.ones(
                    (2, args.micro_batch, args.seq), np.float32)}

    def run(sync: bool) -> dict:
        calls = [0]
        real = loop_mod._device_fetch

        def counting(tree):
            calls[0] += 1
            return real(tree)

        loop_mod._device_fetch = counting
        try:
            t0 = time.perf_counter()
            loop_mod.train(cfg_for(sync), batches(),
                           rng=jax.random.PRNGKey(0))
            wall = time.perf_counter() - t0
        finally:
            loop_mod._device_fetch = real
        return {"host_syncs": calls[0],
                "host_syncs_per_step": round(calls[0] / args.iters, 4),
                "ms_per_step": round(wall * 1e3 / args.iters, 3)}

    sync = run(True)     # also absorbs the shared jit compile
    async_ = run(False)
    return {"sync": sync, "async": async_,
            "sync_reduction_x": round(
                sync["host_syncs"] / max(async_["host_syncs"], 1), 1)}


def _bench_serving(args) -> dict:
    import jax
    import numpy as np

    from megatron_tpu.config import ModelConfig, ServingConfig
    from megatron_tpu.inference.generation import Generator
    from megatron_tpu.models import language_model as lm
    from megatron_tpu.serving import SamplingOptions, ServingEngine

    cfg = ModelConfig(
        num_layers=args.layers, hidden_size=args.hidden,
        num_attention_heads=args.heads,
        num_kv_heads=max(args.heads // 2, 1), vocab_size=args.vocab,
        seq_length=args.seq, max_position_embeddings=args.seq,
        make_vocab_size_divisible_by=64,
        compute_dtype="bfloat16").derived()
    params = lm.model_init(jax.random.PRNGKey(0), cfg)
    gen = Generator(params, cfg, eos_id=0, pad_id=0)
    rs = np.random.RandomState(0)
    prompts = [rs.randint(1, cfg.vocab_size, 24).tolist()
               for _ in range(args.requests)]

    def run(K: int) -> dict:
        serving = ServingConfig(num_slots=args.slots,
                                max_queue=max(args.requests, 64),
                                decode_sync_interval=K)
        with ServingEngine(gen, serving) as eng:
            # warmup compiles (prefill buckets + the one decode trace)
            eng.generate(prompts[0], 2, SamplingOptions(temperature=1.0),
                         seed=0)
            t0 = time.monotonic()
            reqs = [eng.submit(p, args.new,
                               SamplingOptions(temperature=1.0),
                               seed=i) for i, p in enumerate(prompts)]
            for r in reqs:
                r.result(timeout=600)
            wall = time.monotonic() - t0
            snap = eng.metrics.snapshot()
        toks = snap["tokens_generated"]
        return {"decode_sync_interval": K,
                "tokens": int(toks),
                "decode_steps": int(snap["decode_steps"]),
                "host_syncs": int(snap["host_syncs"]),
                "syncs_per_step": round(snap["host_syncs"]
                                        / max(snap["decode_steps"], 1),
                                        4),  # == 1/K by construction
                "syncs_per_token": round(snap["host_syncs"]
                                         / max(toks, 1), 4),
                "wasted_decode_steps": int(
                    snap.get("wasted_decode_steps", 0)),
                "prompts_per_prefill": round(
                    snap.get("prompts_per_prefill", 1.0), 2),
                "tokens_per_s": round(toks / max(wall, 1e-9), 1)}

    base = run(1)
    k = run(args.sync_k)
    return {"k1": base, "k": k,
            "sync_reduction_x": round(
                base["syncs_per_token"]
                / max(k["syncs_per_token"], 1e-9), 1)}


def main(argv=None):
    ensure_env_platform()
    p = argparse.ArgumentParser("bench_sync", description=__doc__)
    p.add_argument("--out", default="/tmp/bench_sync.log")
    p.add_argument("--iters", type=int, default=24)
    p.add_argument("--log_interval", type=int, default=8)
    p.add_argument("--micro_batch", type=int, default=2)
    p.add_argument("--requests", type=int, default=8)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--new", type=int, default=24)
    p.add_argument("--sync_k", type=int, default=4,
                   help="decode_sync_interval for the K arm")
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--seq", type=int, default=128)
    args = p.parse_args(argv)

    import jax
    dev = jax.devices()[0]
    record = {
        "bench": "sync_cadence",
        "device": getattr(dev, "device_kind", dev.platform),
        "training": _bench_training(args),
        "serving": _bench_serving(args),
    }
    line = json.dumps(record)
    print(line, flush=True)
    with open(args.out, "w") as f:
        f.write(line + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
