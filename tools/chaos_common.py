"""Shared scaffolding for the serving chaos tools.

`chaos_serve.py`, `chaos_router.py`, and `chaos_upgrade.py` each grew
their own copy of the same harness pieces (tiny engine/router builders,
serial oracles, outcome resolvers, checkpoint publish helpers) — three
drifting copies of load-bearing test scaffolding. This module is the
single copy they (and the seeded `chaos_mesh.py` conformance engine)
import.

Record contract: every chaos tool emits ONE line of JSON on stdout via
`emit_record`, and every record carries a `seed` field — a CI-logged
failure line is reproducible from the log line alone (the scripted
drills run fixed scenarios, so their seed is the fixed workload seed 0;
chaos_mesh's records carry the sampled seed that regenerates config +
workload + fault schedule).

Import side effects: none (jax imports live inside the builders, so
`force_host_devices` can still set XLA flags first).
"""
from __future__ import annotations

import json
import os
from typing import Optional


def force_host_devices(n: int = 4) -> None:
    """Force an n-virtual-device CPU host platform BEFORE jax
    initializes (the conftest trick — disaggregated / tp drills need
    2 replicas x 2 chip groups). The caller's flags win if already
    set."""
    if "cpu" in os.environ.get("JAX_PLATFORMS", "cpu"):
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()


def tiny_model_cfg(compute: str = "bfloat16", hidden: int = 64,
                   num_kv_heads: int = 1, num_heads: int = 2,
                   sliding_window: Optional[int] = None,
                   attention_impl: Optional[str] = None):
    """The chaos tools' shared tiny model: 2 layers, vocab 128,
    seq 128. `sliding_window` + attention_impl='flash' builds the
    ROLLING pool flavor for the capability-matrix sweeps."""
    from megatron_tpu.config import ModelConfig
    kw = {}
    if sliding_window is not None:
        kw["sliding_window"] = int(sliding_window)
    if attention_impl is not None:
        kw["attention_impl"] = attention_impl
    return ModelConfig(num_layers=2, hidden_size=hidden,
                       num_attention_heads=num_heads,
                       num_kv_heads=num_kv_heads,
                       vocab_size=128, seq_length=128,
                       max_position_embeddings=128,
                       make_vocab_size_divisible_by=64,
                       compute_dtype=compute, **kw).derived()


def auto_compute_dtype(serving_kwargs: dict) -> str:
    """bf16 activations (the production numeric path) EXCEPT when the
    block-native kernel or the LoRA adapter bank is drilled: the
    drills pin engine outputs token-exact vs a serial oracle, and the
    kernel's fp32 online softmax / the adapters' factored-vs-MERGED-
    weights comparison only match the oracle under fp32 activations
    (bf16 rounds the scores — a flipped greedy token there is
    numerics, not a bug). Bracketed / whole-region / adapterless arms
    keep their bf16 coverage."""
    return ("float32" if serving_kwargs.get("block_native_attn")
            or serving_kwargs.get("adapter_slots")
            else "bfloat16")


def tiny_generator(cfg, seed: int = 0):
    """Seeded params + eos_id=-1 Generator (no early EOS, so request
    lifetimes — and any overload backlog — are deterministic in
    max_new_tokens)."""
    import jax

    from megatron_tpu.inference.generation import Generator
    from megatron_tpu.models import language_model as lm
    params = lm.model_init(jax.random.PRNGKey(seed), cfg)
    return Generator(params, cfg, eos_id=-1, pad_id=0)


def tiny_engine(serving_kwargs, hidden: int = 64,
                compute: Optional[str] = None):
    """(engine, generator) over the shared tiny model; `compute=None`
    applies the `auto_compute_dtype` rule."""
    from megatron_tpu.config import ServingConfig
    from megatron_tpu.serving import ServingEngine
    cfg = tiny_model_cfg(compute or auto_compute_dtype(serving_kwargs),
                         hidden=hidden)
    gen = tiny_generator(cfg)
    serving = ServingConfig(**serving_kwargs).validate(cfg)
    return ServingEngine(gen, serving), gen


def tiny_router(serving_kwargs, n_replicas: int = 2, hidden: int = 64,
                heartbeat_s: float = 2.0, probe_backoff_s: float = 0.2,
                compute: Optional[str] = None, devices_per: int = 0):
    """(router, engines, generator): N full replicas over ONE tiny
    model behind an EngineRouter. `devices_per` slices jax.devices()
    into per-replica windows (disaggregated replicas are a
    (prefill-group, decode-group) pair)."""
    from megatron_tpu.config import ServingConfig
    from megatron_tpu.serving import EngineRouter, ServingEngine
    cfg = tiny_model_cfg(compute or auto_compute_dtype(serving_kwargs),
                         hidden=hidden)
    gen = tiny_generator(cfg)
    serving = ServingConfig(**serving_kwargs).validate(cfg)
    if devices_per:
        import jax
        devs = jax.devices()
        engines = [ServingEngine(gen, serving,
                                 devices=devs[i * devices_per:
                                              (i + 1) * devices_per])
                   for i in range(n_replicas)]
    else:
        engines = [ServingEngine(gen, serving)
                   for _ in range(n_replicas)]
    router = EngineRouter(engines, max_retries=2,
                          heartbeat_timeout_s=heartbeat_s,
                          probe_backoff_s=probe_backoff_s)
    return router, engines, gen


def serial_oracle(gen):
    """Serial ground truth, cached per (prompt, n, seed, sampling):
    `want(prompt, n, seed=0, sampling=None)` — greedy when sampling is
    None. The seeded engine contract (serving/engine.py) makes this
    exact for stochastic seeded requests too (speculative stochastic
    rows excepted — the drills go greedy there)."""
    from megatron_tpu.inference.generation import SamplingParams
    cache = {}

    def want(prompt, n, seed=0, sampling=None):
        sp = sampling if sampling is not None \
            else SamplingParams(temperature=0.0)
        key = (tuple(prompt), n, seed,
               (sp.temperature, sp.top_k, sp.top_p))
        if key not in cache:
            t, lens, _ = gen.generate([list(prompt)], n, sampling=sp,
                                      seed=seed)
            cache[key] = t[0, :lens[0]].tolist()
        return cache[key]

    return want


def resolve_all(reqs, timeout: float = 120.0) -> dict:
    """Resolve every future; classify outcomes. A timeout here IS the
    stranded-future failure the drills exist to catch."""
    out = {"ok": 0, "deadline_504": 0, "unavailable_503": 0,
           "error": 0, "stranded": 0}
    from megatron_tpu.serving import (DeadlineExceededError,
                                      ServiceUnavailableError)
    for r in reqs:
        try:
            r.result(timeout=timeout)
            out["ok"] += 1
        except DeadlineExceededError:
            out["deadline_504"] += 1
        except ServiceUnavailableError:
            out["unavailable_503"] += 1
        except TimeoutError:
            out["stranded"] += 1
        except Exception:  # noqa: BLE001 — typed-enough: it RESOLVED
            out["error"] += 1
    return out


def resolve_exact(reqs, want, timeout: float = 120.0):
    """Resolve every (req, prompt, n) future; count outcomes and pin
    every COMPLETED request token-exact vs the serial oracle."""
    out = {"ok": 0, "error": 0, "stranded": 0}
    exact = True
    for r, prompt, n in reqs:
        try:
            toks, _ = r.result(timeout=timeout)
            out["ok"] += 1
            if toks != want(prompt, n):
                exact = False
        except TimeoutError:
            out["stranded"] += 1
        except Exception:  # noqa: BLE001 — typed-enough: it RESOLVED
            out["error"] += 1
    return out, exact


def pool_mode(block, kernel) -> dict:
    """Serving kwargs for the drilled pool layout. Block mode IS the
    production configuration (docs/serving.md pool-capability matrix),
    so the default drills run with kv_block_size set — and with the
    block-native attention kernel where legal — instead of only ever
    chaos-testing the whole-region layout."""
    kw = {}
    if block:
        kw["kv_block_size"] = int(block)
        if kernel:
            kw["block_native_attn"] = True
    return kw


def make_adapters(cfg, n_adapters: int, rank: int = 4) -> dict:
    """n random nonzero adapters (seeded) -> {adapter_id: factors}."""
    from megatron_tpu.serving.adapters import random_adapter_factors
    return {f"tenant-{a}": random_adapter_factors(cfg, rank, 1000 + a)
            for a in range(n_adapters)}


# ---------------------------------------------------------------------
# multi-process fleet helpers (chaos_fleet / the subprocess SSE tests)
# ---------------------------------------------------------------------
class IntTokenizer:
    """Space-separated-integers tokenizer for replica processes serving
    the tiny chaos model: the fleet wire format is pre-tokenized
    `prompt_tokens`, so only `detokenize` matters — and it must be
    deterministic across processes, not linguistic."""

    eod = 0
    bos = None

    def tokenize(self, s):
        return [int(t) for t in str(s).split()]

    def detokenize(self, ids):
        return " ".join(str(int(t)) for t in ids)


def free_port() -> int:
    """An OS-assigned free TCP port (tiny bind/close race with the
    child's own bind — acceptable for test scaffolding)."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def replica_env() -> dict:
    """Child-process environment for a fleet replica: plain CPU jax,
    no inherited multi-device XLA flags (a replica process is one
    engine on one host device)."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    return env


def spawn_replica(port: int, extra_args=(), stdout=None, stderr=None):
    """Start `tools/chaos_fleet.py --serve_replica` as a real process
    serving the tiny model on 127.0.0.1:port (stdlib HTTP transport).
    Child stdout/stderr default to DEVNULL so the parent keeps the
    one-line record contract on ITS stdout."""
    import subprocess
    import sys
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "chaos_fleet.py")
    return subprocess.Popen(
        [sys.executable, script, "--serve_replica", "--port", str(port),
         *map(str, extra_args)],
        env=replica_env(),
        stdout=stdout if stdout is not None else subprocess.DEVNULL,
        stderr=stderr if stderr is not None else subprocess.DEVNULL)


def wait_replica_ready(addr: str, timeout: float = 120.0,
                       proc=None) -> None:
    """Block until the replica at host:port answers /healthz accepting
    (the tiny model still pays a jit compile at boot). Raises on
    timeout or if `proc` exits first."""
    import time

    from megatron_tpu.serving.remote import RemoteReplica
    probe = RemoteReplica(addr, connect_timeout_s=1.0,
                          read_timeout_s=5.0, max_retries=0)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            raise RuntimeError(
                f"replica {addr} exited with {proc.returncode} before "
                "becoming ready")
        try:
            h = probe.health()
            if h.get("accepting"):
                return
        except Exception:  # noqa: BLE001 — not up yet
            pass
        time.sleep(0.1)
    raise TimeoutError(f"replica {addr} not ready within {timeout:.0f}s")


# ---------------------------------------------------------------------
# checkpoint publish helpers (chaos_upgrade / chaos_mesh live-weight
# schedules)
# ---------------------------------------------------------------------
def mega_cfg(model):
    from megatron_tpu.config import (MegatronConfig, OptimizerConfig,
                                     TrainingConfig)
    return MegatronConfig(
        model=model, optimizer=OptimizerConfig(lr=1e-3),
        training=TrainingConfig(micro_batch_size=1, global_batch_size=2,
                                train_iters=1)).validate(n_devices=1)


def publish_checkpoint(root, model, params, iteration):
    """One manifest-sealed checkpoint publish, as a trainer would."""
    import jax.numpy as jnp

    from megatron_tpu.training.checkpointing import save_checkpoint
    from megatron_tpu.training.train_step import TrainState
    return save_checkpoint(
        root, TrainState(params=params, opt_state=None,
                         iteration=jnp.asarray(iteration, jnp.int32)),
        mega_cfg(model), iteration=iteration)


def corrupt_payload(ckpt_dir):
    """Flip one byte of the largest non-manifest payload file — the
    torn/bit-rotted publish the manifest gate must refuse."""
    import glob
    files = [p for p in glob.glob(os.path.join(ckpt_dir, "**"),
                                  recursive=True)
             if os.path.isfile(p)
             and os.path.basename(p) != "manifest.json"]
    target = max(files, key=os.path.getsize)
    with open(target, "r+b") as f:
        b0 = f.read(1)
        f.seek(0)
        f.write(bytes([b0[0] ^ 0xFF]))


# ---------------------------------------------------------------------
# invariant sweep + record emission
# ---------------------------------------------------------------------
def invariant_sweep(target, reqs=(), oracles=(), strict: bool = True,
                    timeout: float = 120.0) -> dict:
    """Run `serving.invariants.check_all` WITHOUT raising; returns the
    report (report["ok"] / report["violations"]) so a drill can embed
    the sweep verdict in its record next to its own assertions."""
    from megatron_tpu.serving import invariants
    try:
        if strict:
            # the strict sweep reads engine-thread accounting: wait for
            # the grid to go quiet (resolved futures may lead the last
            # eviction's bookkeeping by a beat)
            invariants.wait_quiesced(target, timeout=min(timeout, 30.0))
        return invariants.check_all(target, requests=reqs,
                                    oracles=oracles, strict=strict,
                                    timeout=timeout,
                                    raise_on_violation=False)
    except Exception as e:  # noqa: BLE001 — a crashed sweep is a finding
        return {"ok": False,
                "violations": [f"[sweep-crash] {type(e).__name__}: {e}"]}


def emit_record(record: dict, out: Optional[str], seed=0) -> str:
    """One-line JSON record on stdout (and to `out`): every chaos tool
    carries a `seed` field so a CI-logged failure reproduces from the
    log line alone."""
    record.setdefault("seed", seed)
    line = json.dumps(record)
    print(line, flush=True)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return line
