"""Multi-PROCESS front-door chaos drill: a real fleet of replica
server processes behind the remote router, under seeded fault
schedules — nothing strands, nothing moves a token.

tools/chaos_router.py drills the router over N in-process engines;
this tool crosses the process boundary (docs/serving.md "Front
door"): each replica is `chaos_fleet.py --serve_replica` — a REAL
`MegatronServer --replica_mode` process on its own port, stdlib HTTP
transport — and the parent drives an `EngineRouter` over
`RemoteReplica` clients, so every fault below exercises the actual
wire path (SSE streams, typed transport faults, Last-Event-ID
resume, health probes over TCP). Four drills, seeded:

1. **sigkill**: one replica process is SIGKILLed mid-decode.
   Contract: zero stranded futures, every COMPLETED request
   token-exact vs the parent's serial oracle (failover resubmits by
   seed), the router reports DEGRADED (not down) and keeps accepting;
   after a respawn on the same port the half-open canary re-admits
   the replica — the fleet ends at full strength.
2. **sigstop**: one replica is SIGSTOPped (a wedged process: TCP
   still connects, nothing answers). Contract: health probes time
   out -> missed heartbeats eject it, in-flight streams fail over
   token-exact, and after SIGCONT the canary path re-admits it.
3. **flaky_proxy**: one replica is reached only through a seeded
   fault shim (refuse / truncate-after-N-bytes / added latency on
   every connection). Contract: each injected fault lands as a TYPED
   transport error inside the retry/reconnect/failover machinery —
   outcomes stay token-exact, no bare exceptions escape.
4. **restart**: a replica is SIGKILLed and respawned WHILE traffic
   flows (the mid-storm restart). Contract: traffic submitted across
   the restart window resolves token-exact and the fleet returns to
   full strength.

Every drill finishes with a fleet-mode `invariants.check_all` sweep
(serving/invariants.py): the router aggregates per-replica invariant
reports over HTTP (`GET /invariants`), so per-replica request
conservation + KV accounting + schema run INSIDE each replica
process while the router-level degraded-not-down law runs here. A
replica that is dead at sweep time is recorded unreachable, not
convicted.

Emits ONE JSON record on stdout (and to --out) carrying the seed and
a repro line, so a CI-logged violation reproduces from the log line
alone:

  JAX_PLATFORMS=cpu python tools/chaos_fleet.py --smoke [--out FILE]
  JAX_PLATFORMS=cpu python tools/chaos_fleet.py --seed 7 --replicas 3
"""
from __future__ import annotations

import argparse
import os
import random
import signal
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform
from tools.chaos_common import (IntTokenizer, emit_record, free_port,
                                invariant_sweep,
                                resolve_exact as _resolve_exact,
                                serial_oracle as _serial_oracle,
                                spawn_replica, tiny_generator,
                                tiny_model_cfg, wait_replica_ready)

# the replica processes and the parent's serial oracle must build the
# IDENTICAL tiny model (same seed, same dtype, same binary) — that is
# what makes cross-process token-exactness a real check and not a
# coincidence
REPLICA_SERVING = dict(num_slots=4, max_queue=64,
                       enable_prefix_cache=True, kv_block_size=16)


# ---------------------------------------------------------------------
# replica child mode
# ---------------------------------------------------------------------
def serve_replica(port: int) -> int:
    """`--serve_replica`: run ONE tiny engine as a standalone
    `--replica_mode` server process on 127.0.0.1:port (stdlib
    transport for determinism — no flask dependency in the drill
    path). The parent talks to it exclusively over HTTP."""
    from megatron_tpu.config import ServingConfig
    from megatron_tpu.inference.server import MegatronServer
    cfg = tiny_model_cfg()
    gen = tiny_generator(cfg)
    serving = ServingConfig(replica_mode=True,
                            **REPLICA_SERVING).validate(cfg)
    server = MegatronServer(gen, IntTokenizer(), serving=serving)
    server._run_stdlib("127.0.0.1", port)
    return 0


# ---------------------------------------------------------------------
# parent-side fleet handle
# ---------------------------------------------------------------------
class Fleet:
    """N replica processes + the remote router over them, plus the
    process handles the drills SIGKILL/SIGSTOP."""

    def __init__(self, n: int, heartbeat_s: float = 2.0):
        from megatron_tpu.serving import EngineRouter
        from megatron_tpu.serving.metrics import ServingMetrics
        from megatron_tpu.serving.remote import RemoteReplica
        self.ports = [free_port() for _ in range(n)]
        self.procs = [spawn_replica(p) for p in self.ports]
        for port, proc in zip(self.ports, self.procs):
            wait_replica_ready(f"127.0.0.1:{port}", proc=proc)
        self.counters = ServingMetrics()
        self.replicas = [
            RemoteReplica(f"127.0.0.1:{port}", counters=self.counters,
                          connect_timeout_s=2.0, read_timeout_s=5.0,
                          max_retries=2, digest_interval_s=0.5)
            for port in self.ports]
        self.router = EngineRouter(self.replicas, metrics=self.counters,
                                   max_retries=2,
                                   heartbeat_timeout_s=heartbeat_s,
                                   probe_backoff_s=0.2)

    def respawn(self, i: int) -> None:
        self.procs[i] = spawn_replica(self.ports[i])
        wait_replica_ready(f"127.0.0.1:{self.ports[i]}",
                           proc=self.procs[i])

    def close(self) -> None:
        try:
            self.router.close()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass
        for p in self.procs:
            try:
                if p.poll() is None:
                    p.kill()
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass


def _sampling():
    from megatron_tpu.serving import SamplingOptions
    return SamplingOptions(temperature=0.0)


def submit_batch(router, rng: random.Random, n_reqs: int,
                 new_tokens: int, seed0: int = 0):
    """n_reqs greedy requests over seeded random 4-token prompts
    (vocab 1..127 — 0 is the pad id). Greedy keeps the oracle
    seed-independent; UNIQUE seeds still ride along so the failover
    resubmission path carries them token-exact."""
    sampling = _sampling()
    reqs = []
    for i in range(n_reqs):
        p = [rng.randint(1, 127) for _ in range(4)]
        reqs.append((router.submit(p, new_tokens, sampling,
                                   seed=seed0 + i), p, new_tokens))
    return reqs


def wait_readmitted(fleet: Fleet, timeout: float = 90.0):
    """Drive the half-open re-admission path: DOWN->PROBING needs a
    probe WINDOW and a trial request, so poll router health AND feed
    tiny canary submits until every replica is back in rotation.
    Returns (readmitted, canary_reqs) — the canaries join the drill's
    resolve/sweep so they can never strand silently."""
    sampling = _sampling()
    deadline = time.monotonic() + timeout
    canaries = []
    while time.monotonic() < deadline:
        h = fleet.router.health()
        if h.get("replicas_up", 0) >= len(fleet.replicas):
            return True, canaries
        r = fleet.router.submit([3, 1, 4, 1], 2, sampling, seed=0)
        try:
            r.result(timeout=30)
        except Exception:  # noqa: BLE001 — classified in resolve
            pass
        canaries.append((r, [3, 1, 4, 1], 2))
        time.sleep(0.25)
    return False, canaries


def _drill_wrap(fleet: Fleet, want, name: str, body: dict,
                reqs) -> dict:
    """Shared drill tail: resolve every future token-exact, then run
    the fleet-mode invariant sweep over HTTP."""
    outcomes, exact = _resolve_exact(reqs, want)
    inv = invariant_sweep(fleet.router, [r for r, _, _ in reqs],
                          strict=True)
    body.update({
        "drill": name, "outcomes": outcomes, "exact": exact,
        "stranded": outcomes["stranded"],
        "invariants_ok": bool(inv.get("ok")),
        "violations": [str(v) for v in inv.get("violations", [])],
    })
    body["ok"] = (exact and outcomes["stranded"] == 0
                  and body["invariants_ok"]
                  and all(body.get(k, True) for k in
                          ("degraded_not_down", "post_ok",
                           "readmitted", "typed_only")))
    return body


# ---------------------------------------------------------------------
# drills
# ---------------------------------------------------------------------
def drill_sigkill(fleet: Fleet, want, rng: random.Random,
                  new_tokens: int, n_reqs: int) -> dict:
    victim = rng.randrange(len(fleet.procs))
    reqs = submit_batch(fleet.router, rng, n_reqs, new_tokens)
    time.sleep(0.2)  # let decode start so the kill lands mid-stream
    fleet.procs[victim].kill()
    fleet.procs[victim].wait()
    # the front door still serves after losing a process
    post = fleet.router.submit([9, 9, 8, 7], 4, _sampling(), seed=99)
    post_toks, _ = post.result(timeout=60)
    health = fleet.router.health()
    # bring the fleet back to full strength: same port, new process
    fleet.respawn(victim)
    readmitted, canaries = wait_readmitted(fleet)
    return _drill_wrap(fleet, want, "sigkill", {
        "victim": victim,
        "post_ok": post_toks == want([9, 9, 8, 7], 4),
        "degraded_not_down": health["accepting"],
        "state_after_kill": health["state"],
        "readmitted": readmitted,
    }, reqs + [(post, [9, 9, 8, 7], 4)] + canaries)


def drill_sigstop(fleet: Fleet, want, rng: random.Random,
                  new_tokens: int, n_reqs: int) -> dict:
    victim = rng.randrange(len(fleet.procs))
    reqs = submit_batch(fleet.router, rng, n_reqs, new_tokens)
    time.sleep(0.2)
    os.kill(fleet.procs[victim].pid, signal.SIGSTOP)
    try:
        # more traffic INTO the wedge: probes time out, heartbeats
        # lapse, the wedged replica ejects, this work fails over
        reqs += submit_batch(fleet.router, rng, n_reqs, new_tokens,
                             seed0=100)
        time.sleep(0.5)
        health = fleet.router.health()
    finally:
        os.kill(fleet.procs[victim].pid, signal.SIGCONT)
    readmitted, canaries = wait_readmitted(fleet)
    return _drill_wrap(fleet, want, "sigstop", {
        "victim": victim,
        "degraded_not_down": health["accepting"],
        "readmitted": readmitted,
    }, reqs + canaries)


class FlakyProxy(threading.Thread):
    """Seeded per-connection TCP fault shim in front of ONE replica:
    each accepted connection draws a verdict from the seeded rng —
    refuse (close before a byte), cut (truncate the upstream->client
    stream after a seeded byte budget: a mid-body reset / truncated
    SSE), delay (per-chunk added latency), or clean pump. The client
    side sees exactly the fault taxonomy remote.py types."""

    def __init__(self, upstream_port: int, seed: int,
                 refuse_p: float = 0.15, cut_p: float = 0.15,
                 delay_s: float = 0.03):
        super().__init__(daemon=True, name="flaky-proxy")
        self.upstream_port = upstream_port
        self.port = free_port()
        self._rng = random.Random(seed)
        self.refuse_p, self.cut_p, self.delay_s = refuse_p, cut_p, delay_s
        self.faults = {"refuse": 0, "cut": 0, "delay": 0, "clean": 0}
        self._listen = socket.socket()
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind(("127.0.0.1", self.port))
        self._listen.listen(64)
        self._stop = threading.Event()

    def run(self):
        while not self._stop.is_set():
            try:
                client, _ = self._listen.accept()
            except OSError:
                return
            # verdicts draw in ACCEPT order on this one thread, so a
            # seed pins the fault schedule
            r = self._rng.random()
            budget = self._rng.randint(64, 600)
            verdict = ("refuse" if r < self.refuse_p
                       else "cut" if r < self.refuse_p + self.cut_p
                       else "delay" if r < self.refuse_p + self.cut_p
                       + 0.25 else "clean")
            self.faults[verdict] += 1
            threading.Thread(target=self._handle, daemon=True,
                             args=(client, verdict, budget)).start()

    def _handle(self, client, verdict: str, budget: int):
        try:
            if verdict == "refuse":
                client.close()
                return
            up = socket.create_connection(
                ("127.0.0.1", self.upstream_port), timeout=5.0)
        except OSError:
            client.close()
            return

        def pump(src, dst, limit=None, delay=0.0):
            moved = 0
            try:
                while True:
                    data = src.recv(4096)
                    if not data:
                        break
                    if limit is not None and moved + len(data) > limit:
                        data = data[:max(0, limit - moved)]
                        if data:
                            dst.sendall(data)
                        break  # truncate: reset mid-body
                    if delay:
                        time.sleep(delay)
                    dst.sendall(data)
                    moved += len(data)
            except OSError:
                pass
            finally:
                for s in (src, dst):
                    try:
                        s.close()
                    except OSError:
                        pass

        threading.Thread(target=pump, args=(client, up),
                         daemon=True).start()
        pump(up, client,
             limit=budget if verdict == "cut" else None,
             delay=self.delay_s if verdict == "delay" else 0.0)

    def close(self):
        self._stop.set()
        try:
            self._listen.close()
        except OSError:
            pass


def drill_flaky_proxy(fleet: Fleet, want, rng: random.Random,
                      new_tokens: int, n_reqs: int, seed: int) -> dict:
    """A SECOND router whose first replica is only reachable through
    the fault shim (the other direct) — the shared replica processes
    serve both routers concurrently, which is itself load."""
    from megatron_tpu.serving import EngineRouter, ServiceUnavailableError
    from megatron_tpu.serving.metrics import ServingMetrics
    from megatron_tpu.serving.remote import RemoteReplica
    proxy = FlakyProxy(fleet.ports[0], seed=seed)
    proxy.start()
    counters = ServingMetrics()
    replicas = [
        RemoteReplica(f"127.0.0.1:{proxy.port}", counters=counters,
                      connect_timeout_s=2.0, read_timeout_s=5.0,
                      max_retries=2, digest_interval_s=0.5),
        RemoteReplica(f"127.0.0.1:{fleet.ports[-1]}", counters=counters,
                      connect_timeout_s=2.0, read_timeout_s=5.0,
                      max_retries=2, digest_interval_s=0.5)]
    router = EngineRouter(replicas, metrics=counters, max_retries=2,
                          heartbeat_timeout_s=2.0, probe_backoff_s=0.2)
    typed_only = True
    reqs = []
    try:
        sampling = _sampling()
        for i in range(n_reqs):
            p = [rng.randint(1, 127) for _ in range(4)]
            try:
                reqs.append((router.submit(p, new_tokens, sampling,
                                           seed=i), p, new_tokens))
            except ServiceUnavailableError:
                pass  # typed admission-time refusal: acceptable
            except Exception:  # noqa: BLE001 — the drill's whole point
                typed_only = False
        outcomes, exact = _resolve_exact(reqs, want)
        snap = router.aggregate_snapshot()
        inv = invariant_sweep(router, [r for r, _, _ in reqs],
                              strict=True)
    finally:
        router.close()
        proxy.close()
    body = {
        "drill": "flaky_proxy", "outcomes": outcomes, "exact": exact,
        "stranded": outcomes["stranded"], "typed_only": typed_only,
        "proxy_faults": proxy.faults,
        "remote_retries": snap.get("router_remote_retries", 0.0),
        "remote_timeouts": snap.get("router_remote_timeouts", 0.0),
        "invariants_ok": bool(inv.get("ok")),
        "violations": [str(v) for v in inv.get("violations", [])],
    }
    body["ok"] = (exact and outcomes["stranded"] == 0 and typed_only
                  and body["invariants_ok"])
    return body


def drill_restart(fleet: Fleet, want, rng: random.Random,
                  new_tokens: int, n_reqs: int) -> dict:
    """Mid-storm restart: the kill AND the respawn both land while
    traffic is in flight."""
    victim = rng.randrange(len(fleet.procs))
    reqs = submit_batch(fleet.router, rng, n_reqs, new_tokens)
    time.sleep(0.15)
    fleet.procs[victim].kill()
    fleet.procs[victim].wait()
    # storm continues while the process is gone...
    reqs += submit_batch(fleet.router, rng, n_reqs, new_tokens,
                         seed0=200)
    # ...and while it comes back
    fleet.respawn(victim)
    reqs += submit_batch(fleet.router, rng, n_reqs, new_tokens,
                         seed0=300)
    readmitted, canaries = wait_readmitted(fleet)
    return _drill_wrap(fleet, want, "restart", {
        "victim": victim, "readmitted": readmitted,
    }, reqs + canaries)


# ---------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------
DRILLS = ("sigkill", "sigstop", "flaky_proxy", "restart")


def run_chaos(seed: int, n_replicas: int, new_tokens: int,
              n_reqs: int, drills) -> dict:
    rng = random.Random(seed)
    fleet = Fleet(n_replicas)
    want = _serial_oracle(tiny_generator(tiny_model_cfg()))
    results = {}
    fns = {"sigkill": drill_sigkill, "sigstop": drill_sigstop,
           "restart": drill_restart}
    try:
        for name in drills:
            try:
                if name == "flaky_proxy":
                    results[name] = drill_flaky_proxy(
                        fleet, want, rng, new_tokens, n_reqs, seed)
                elif name in fns:
                    results[name] = fns[name](fleet, want, rng,
                                              new_tokens, n_reqs)
                else:
                    raise SystemExit(f"unknown drill {name!r}")
            except SystemExit:
                raise
            except Exception as e:  # noqa: BLE001 — a crashed drill
                # is a VIOLATION with a record, not a stack trace
                # without one (the record carries the repro line)
                results[name] = {
                    "drill": name, "ok": False, "invariants_ok": False,
                    "crash": f"{type(e).__name__}: {e}"}
        snap = fleet.router.aggregate_snapshot()
    finally:
        fleet.close()
    completed = all(r.get("ok") for r in results.values())
    record = {
        "tool": "chaos_fleet", "completed": completed,
        "replicas": n_replicas, "new_tokens": new_tokens,
        "drills": results,
        "invariants_ok": all(r.get("invariants_ok")
                             for r in results.values()),
        "fleet_counters": {
            k: snap.get(k, 0.0)
            for k in ("router_failovers", "router_retries",
                      "router_remote_timeouts", "router_remote_retries",
                      "router_probe_failures", "fleet_replicas_up")},
        "repro": (f"python tools/chaos_fleet.py --seed {seed} "
                  f"--replicas {n_replicas} --new_tokens {new_tokens} "
                  f"--requests {n_reqs}"),
    }
    return record


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--serve_replica", action="store_true",
                    help="child mode: run ONE replica server process")
    ap.add_argument("--port", type=int, default=0,
                    help="child mode: port to serve on")
    ap.add_argument("--seed", type=int, default=0,
                    help="fault-schedule seed (printed in the record)")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--new_tokens", type=int, default=12)
    ap.add_argument("--requests", type=int, default=8,
                    help="requests per drill batch")
    ap.add_argument("--drills", type=str, default=",".join(DRILLS),
                    help="comma list from: " + ",".join(DRILLS))
    ap.add_argument("--smoke", action="store_true",
                    help="2 replicas, sigkill drill only (CI extras)")
    ap.add_argument("--out", type=str, default=None,
                    help="also write the JSON record here")
    args = ap.parse_args(argv)

    ensure_env_platform()
    if args.serve_replica:
        if not args.port:
            ap.error("--serve_replica requires --port")
        return serve_replica(args.port)

    drills = [d for d in args.drills.split(",") if d]
    if args.smoke:
        args.replicas, args.new_tokens, args.requests = 2, 12, 6
        drills = ["sigkill"]

    record = run_chaos(args.seed, args.replicas, args.new_tokens,
                       args.requests, drills)
    emit_record(record, args.out, seed=args.seed)
    if not record["completed"]:
        print(f"VIOLATION — repro: {record['repro']}",
              file=sys.stderr)
    return 0 if record["completed"] else 1


if __name__ == "__main__":
    sys.exit(main())
