"""Deterministic chaos-mesh conformance: seeded fault schedules +
system-wide invariants over the full serving matrix.

The scripted drills (chaos_serve / chaos_router / chaos_upgrade) each
walk ONE hand-written scenario. This tool is the FoundationDB-style
complement: from a single ``--seed`` it

1. **samples a serving config** from the capability matrix — pool
   layout (whole-region / block / block-native kernel), prefix cache +
   chunked prefill + host tier, speculative decoding, adapters,
   priorities/preemption/shedding, serving_tp, disaggregation with
   per-phase widths (prefill_tp / decode_tp — asymmetric splits
   included), pipeline-sharded decode (serving_pp stage chains,
   optionally wave-interleaved), replicas, int8 KV, rolling
   sliding-window models —
   driving the REAL
   ``ServingConfig.validate()`` as the rejection filter, so illegal
   combinations (rolling x speculative, kernel x sliding-window, ...)
   are exercised as LOUD-rejection cases (recorded per run), never
   silently skipped;
2. **generates a randomized workload** — shared prefixes, priorities,
   hopeless deadlines, adapter mix, seeded stochastic sampling, a
   streaming consumer, mid-flight cancels, grammar-constrained
   requests (seeded draws from a bounded/cyclic regex + json_schema
   pool, checked by the grammar-validity law AND token-exact vs a
   quiet single-slot oracle engine), and n=2 COW fan-out requests
   (each sample independently seed-checked);
3. **interleaves a randomized fault schedule** — engine-step faults
   drawn from the extended `FaultInjector` (serve_delay / serve_crash /
   serve_nan / serve_host_corrupt / serve_adapter_corrupt) plus
   harness actions (queue-overload burst, replica kill, live-weight
   swap, torn/corrupt publish) — then
4. **checks the system-wide invariants** (serving/invariants.py)
   during and after the storm: request conservation, typed terminals
   (zero stranded futures), token-exactness of every COMPLETED request
   vs a serial oracle keyed by its (seed, sampling, adapter,
   weight-version), KV-block accounting + namespace isolation, metrics
   schema stability, and healthz consistency.

A failing run prints the one-line repro (``--seed S [--require ...]``)
with the violated laws. ``--minutes N`` soak mode walks seeds until
the budget expires; ``--smoke`` runs a small fixed seed set covering
adapters, disaggregation, a live-weight swap, the brownout
degradation ladder, and a pipeline-sharded stage chain (bench extras
+ the slow-tier test run it);
``--inject_violation`` deliberately drops a
terminal transition after a green run to prove the checker is not
vacuous (test-pinned).

  JAX_PLATFORMS=cpu python tools/chaos_mesh.py --seed 7
  JAX_PLATFORMS=cpu python tools/chaos_mesh.py --smoke [--out FILE]
  JAX_PLATFORMS=cpu python tools/chaos_mesh.py --minutes 10
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform
from tools import chaos_common as cc

N_DEVICES = 4  # forced host platform: disagg/tp configs need 2x2

# smoke seed set: each (seed, require) pair is a full repro line; the
# `require` tokens bias the sampler toward a matrix corner so the
# fixed smoke always covers adapters, disaggregation, a live-weight
# swap, structured output, and n-best fan-out regardless of what the
# bare seed would draw
SMOKE_SEEDS = [(7, ("adapters",)), (11, ("disagg",)), (23, ("swap",)),
               (31, ("structured",)), (43, ("fanout",)),
               (53, ("phases",)),  # asymmetric per-phase disagg split
               (61, ("degrade",)),  # brownout ladder + SLO accounting
               (71, ("pp",))]  # pipeline-sharded (layer-staged) decode

# the seeded grammar pool: every entry compiles against the tiny
# model's vocab-128 identity token table (token i <-> chr(i)), so
# masked decoding emits literal ASCII. `bounded` entries have an
# acyclic DFA — the workload gives them max_new_tokens >= the longest
# path, which arms the law-7 PARSE check (final_text_valid), not just
# per-token legality; the cyclic entry keeps unbounded-grammar
# coverage (validity-only).
GRAMMAR_POOL = [
    {"type": "regex", "pattern": "(ab|ba){2,3}"},
    {"type": "regex", "pattern": "[0-9]{2,5}"},
    {"type": "regex", "pattern": "(foo|bar|quux)"},
    {"type": "regex", "pattern": "a[bc]*d"},  # cyclic: validity-only
    {"type": "json_schema",
     "schema": {"type": "integer", "minimum": 0, "maxDigits": 3}},
]


# ---------------------------------------------------------------------
# 1. seeded config sampling (validate() as the rejection filter)
# ---------------------------------------------------------------------
def sample_config(rng: random.Random, require=()):
    """Sample (model_kwargs, serving_kwargs, rejections) — resampling
    through ServingConfig.validate() until a LEGAL point of the
    capability matrix comes up; every rejection is recorded (matrix
    exclusions exercised loudly, not skipped). The fault schedule is
    sampled separately (build_fault_injector / build_actions)."""
    from megatron_tpu.config import ServingConfig
    rejections = []
    for _ in range(200):
        rolling = rng.random() < 0.15 and "disagg" not in require \
            and "tp" not in require and "phases" not in require \
            and "pp" not in require
        model_kwargs = dict(compute="float32", num_kv_heads=2)
        if rolling:
            model_kwargs.update(sliding_window=64,
                                attention_impl="flash")
        blocks = rng.random() < 0.7
        kw = dict(
            num_slots=2, max_queue=64, max_len=128,
            max_engine_restarts=2,
            kv_block_size=16 if blocks else None,
            block_native_attn=blocks and rng.random() < 0.5,
            enable_prefix_cache=rng.random() < 0.6,
            prefill_chunk=8 if rng.random() < 0.3 else None,
            retained_slots=rng.choice([None, None, 1]),
            speculative_k=4 if rng.random() < 0.35 else 0,
            adapter_slots=2 if rng.random() < 0.35 else 0,
            kv_dtype="int8" if rng.random() < 0.2 else None,
            shed_on_overload=rng.random() < 0.5,
            serving_tp=2 if rng.random() < 0.2 else 1,
            disaggregate_prefill=rng.random() < 0.25,
            num_replicas=2 if rng.random() < 0.4 else 1,
        )
        # per-phase widths (serving/topology.py): disaggregated configs
        # draw independent prefill_tp/decode_tp — asymmetric splits are
        # the point. A small slice deliberately draws ILL-FORMED
        # corners: per-phase widths without disaggregation (unequal
        # widths on a shared mesh) or a width that does not divide the
        # tiny model's kv heads — both must come back as LOUD
        # validate() rejections, never silent coercion.
        if kw["disaggregate_prefill"] and rng.random() < 0.35:
            kw["prefill_tp"] = rng.choice([1, 2])
            kw["decode_tp"] = rng.choice([1, 2])
        elif rng.random() < 0.08:
            kw["prefill_tp"] = rng.choice([2, 3])
        # pipeline-sharded serving axis (serving/topology.py
        # "Pipeline-sharded serving"): a slice draws a 2-stage
        # layer-staged decode chain, half of it wave-interleaved. The
        # draw deliberately lands on ILLEGAL pairings too (pp x
        # disagg, pp x whole-region pool, pp x kernel, pp x host
        # tier, waves x speculative) — all must come back as LOUD
        # validate() rejections, never silent coercion.
        if rng.random() < 0.2:
            kw["serving_pp"] = 2
            if rng.random() < 0.5:
                kw["pp_waves"] = 2
        if rng.random() < 0.5:
            kw.update(priority_levels=2,
                      preemption=rng.random() < 0.7)
        # brownout ladder + SLO accounting axis (serving/degrade.py):
        # degraded admissions stay oracle-exact because the
        # token-exact law keys off the request's EFFECTIVE
        # max_new_tokens, not the spec it was submitted with
        if rng.random() < 0.3:
            kw.update(degrade_ladder=rng.choice([2, 4]),
                      degrade_max_new_tokens=6)
        if rng.random() < 0.25:
            kw.update(slo_ttft_ms=30_000.0, slo_itl_p99_ms=30_000.0)
        if rng.random() < 0.35:
            kw["engine_step_timeout_s"] = 2.0
        if kw["enable_prefix_cache"] and kw["kv_block_size"] \
                and rng.random() < 0.4:
            kw["host_kv_bytes"] = 1 << 22
        # require biases (part of the repro line): force the matrix
        # corner the caller wants covered
        if "adapters" in require:
            kw["adapter_slots"] = 2
        if "disagg" in require:
            kw.update(disaggregate_prefill=True, kv_block_size=16)
        if "router" in require:
            kw["num_replicas"] = 2
        if "tp" in require:
            kw["serving_tp"] = 2
        if "phases" in require:
            # asymmetric per-phase disagg split (1 prefill chip : 2
            # decode chips — fits the 4-device budget with slack)
            kw.update(disaggregate_prefill=True, kv_block_size=16,
                      serving_tp=1, prefill_tp=1, decode_tp=2,
                      num_replicas=1)
        if "degrade" in require:
            # full brownout ladder with hair-trigger raise edges and
            # minimal dwell so the mesh storm actually walks it under
            # a 2-slot engine, plus live SLO accounting
            kw.update(degrade_ladder=4,
                      degrade_raise_at=(0.25, 0.5, 1.0, 2.0),
                      degrade_dwell_up=1, degrade_dwell_down=2,
                      degrade_max_new_tokens=6,
                      shed_on_overload=True, priority_levels=2,
                      slo_ttft_ms=30_000.0, slo_itl_p99_ms=30_000.0)
        if "pp" in require:
            # layer-staged decode chain (2 stages x width 1) with the
            # second wave interleaved on the slot grid. The staged
            # exclusions (disagg, kernel, host tier, explicit prefill
            # width, speculative under waves) would validate()-reject,
            # so pin the legal corner; the bare engine keeps fan-out
            # admissible, exercising COW forks over the staged pool
            kw.update(serving_pp=2, decode_tp=1, pp_waves=2,
                      kv_block_size=16, block_native_attn=False,
                      disaggregate_prefill=False, speculative_k=0,
                      serving_tp=1, num_replicas=1)
            kw.pop("prefill_tp", None)
            kw.pop("host_kv_bytes", None)
        if "fanout" in require:
            # fan-out aggregates are engine-level (the router's retry
            # pump refuses best_of > 1 typed) — pin a bare engine so
            # the required n=2 specs actually admit
            kw["num_replicas"] = 1
        # resource clamp (not a matrix exclusion): N_DEVICES virtual
        # devices must fit num_replicas x devices_per_engine — the
        # same per-phase arithmetic serving/topology.devices_per_engine
        # resolves (decode width + prefill width when disaggregated)
        ptp = kw.get("prefill_tp") or kw["serving_tp"]
        dtp = kw.get("decode_tp") or kw["serving_tp"]
        per = dtp * kw.get("serving_pp", 1) \
            + (ptp if kw["disaggregate_prefill"] else 0)
        if per * kw["num_replicas"] > N_DEVICES:
            kw["num_replicas"] = 1
        if per > N_DEVICES:
            kw["serving_tp"] = 1
            kw.pop("prefill_tp", None)
            kw.pop("decode_tp", None)
            kw.pop("serving_pp", None)
            kw.pop("pp_waves", None)
        model = cc.tiny_model_cfg(**model_kwargs)
        try:
            ServingConfig(**kw).validate(model)
        except AssertionError as e:
            rejections.append({
                "kwargs": {k: v for k, v in kw.items() if v},
                "rolling": rolling,
                "rejected": str(e).splitlines()[0][:160],
            })
            continue
        return model_kwargs, kw, rejections
    raise RuntimeError(
        f"sample_config: 200 consecutive validate() rejections "
        f"(sampler/matrix drift?): last={rejections[-1]}")


# ---------------------------------------------------------------------
# 2. seeded workload
# ---------------------------------------------------------------------
def build_workload(rng: random.Random, serving_kw: dict,
                   n_requests: int, new_tokens: int, require=()):
    """Randomized request specs: shared prefixes, priorities, hopeless
    deadlines, adapter mix, seeded stochastic sampling (greedy-only
    when speculative — stochastic spec rows are distribution-correct,
    not serial-bit-reproducing), grammar-constrained requests from
    GRAMMAR_POOL, and n=2 fan-out requests (bare engines only — the
    router refuses best_of > 1 typed). The grammar draw rides the SAME
    seeded rng stream as everything else, so the ``--seed`` repro line
    regenerates the exact grammars too. Returns (specs, cancel_idx,
    stream_idx)."""
    from megatron_tpu.serving import SamplingOptions
    from megatron_tpu.serving.structured import compile_response_format
    prefixes = [[rng.randrange(2, 120) for _ in range(rng.choice([16, 20]))]
                for _ in range(2)]
    adapters = ([None, "tenant-0", "tenant-1"]
                if serving_kw.get("adapter_slots") else [None])
    fanout_ok = serving_kw.get("num_replicas", 1) == 1
    specs = []
    for i in range(n_requests):
        if rng.random() < 0.4:
            prompt = list(rng.choice(prefixes)) + \
                [rng.randrange(2, 120) for _ in range(rng.randrange(1, 5))]
        else:
            prompt = [rng.randrange(2, 120)
                      for _ in range(rng.randrange(3, 20))]
        if serving_kw.get("speculative_k") or rng.random() < 0.6:
            sampling = SamplingOptions(temperature=0.0)
        else:
            sampling = SamplingOptions(temperature=0.8, top_k=5)
        specs.append(dict(
            prompt=prompt,
            max_new_tokens=rng.randrange(3, new_tokens + 1),
            sampling=sampling,
            seed=rng.randrange(1 << 20),
            priority=(rng.randrange(2)
                      if serving_kw.get("priority_levels", 1) > 1 else 0),
            deadline_s=(0.001 if rng.random() < 0.12 else None),
            adapter_id=rng.choice(adapters),
        ))
        # structured axis: grammar-constrained decode under the storm
        # (law 7 checks FSM legality + parse; the quiet-engine oracle
        # pins the masked stream token-exact)
        if rng.random() < 0.25 or ("structured" in require and i == 1):
            rf = rng.choice(GRAMMAR_POOL)
            fsm = compile_response_format(rf, 128)
            specs[i]["response_format"] = rf
            specs[i]["deadline_s"] = None  # completed streams feed law 7
            if fsm.max_path_len is not None:
                # bounded grammar: budget covers the longest path, so
                # the sweep's PARSE check arms (not just legality)
                specs[i]["max_new_tokens"] = fsm.max_path_len
        # fan-out axis: n=2 COW samples off one prefill (num_slots=2
        # caps best_of at 2 here); composes with structured draws
        if fanout_ok and (rng.random() < 0.2
                          or ("fanout" in require and i == 1)):
            specs[i]["n"] = 2
            specs[i]["best_of"] = 2
        # at least one deadline-less greedy request so the storm
        # always has an oracle-checkable completion
        if i == 0:
            specs[0]["deadline_s"] = None
            specs[0]["sampling"] = SamplingOptions(temperature=0.0)
    cancel_idx = rng.randrange(n_requests) if rng.random() < 0.6 else None
    stream_idx = rng.randrange(n_requests)
    return specs, cancel_idx, stream_idx


def build_fault_injector(rng: random.Random, serving_kw: dict):
    """Seeded engine-step fault schedule over the EXTENDED FaultInjector
    kinds (docs/resilience.md 'Chaos conformance' has the grammar)."""
    from megatron_tpu.resilience import FaultInjector
    kinds = []
    kw = dict(serve_delay_calls={}, serve_crash_calls=set(),
              serve_nan_calls={}, serve_host_corrupt_calls=set(),
              serve_adapter_corrupt_calls=set())
    if rng.random() < 0.5:
        kw["serve_crash_calls"].add(rng.randrange(4, 12))
        kinds.append("serve_crash")
    if rng.random() < 0.5:
        kw["serve_nan_calls"][rng.randrange(3, 10)] = rng.randrange(2)
        kinds.append("serve_nan")
    if rng.random() < 0.35:
        stall = (3.0 if serving_kw.get("engine_step_timeout_s")
                 else 0.3)  # past-watchdog wedge vs plain stall
        kw["serve_delay_calls"][rng.randrange(3, 10)] = stall
        kinds.append("serve_delay")
    if serving_kw.get("host_kv_bytes"):
        kw["serve_host_corrupt_calls"].add(rng.randrange(5, 20))
        kinds.append("serve_host_corrupt")
    if serving_kw.get("adapter_slots") and rng.random() < 0.5:
        kw["serve_adapter_corrupt_calls"].add(rng.randrange(5, 20))
        kinds.append("serve_adapter_corrupt")
    return FaultInjector(**kw), kinds


def build_actions(rng: random.Random, serving_kw: dict, require=()):
    """Harness-level fault actions (the kinds an injector fault point
    cannot reach): overload burst, replica kill, live-weight swap,
    torn (corrupt) publish."""
    actions = []
    if rng.random() < 0.7:
        actions.append("burst")
    if serving_kw.get("num_replicas", 1) > 1 and rng.random() < 0.5:
        actions.append("kill_replica")
    do_swap = "swap" in require or rng.random() < 0.3
    if do_swap:
        if rng.random() < 0.5:
            actions.append("swap_corrupt")  # refused BEFORE the good one
        actions.append("swap_good")
    rng.shuffle(actions)
    return actions


# ---------------------------------------------------------------------
# 3+4. the storm + invariant sweeps
# ---------------------------------------------------------------------
def _build_target(model_kwargs: dict, serving_kw: dict):
    """(target, engines, gen) — a bare engine or an EngineRouter fleet,
    devices sliced per replica when the topology needs them."""
    import jax

    from megatron_tpu.config import ServingConfig
    from megatron_tpu.serving import EngineRouter, ServingEngine
    model = cc.tiny_model_cfg(**model_kwargs)
    gen = cc.tiny_generator(model, seed=0)
    serving = ServingConfig(**serving_kw).validate(model)
    n_rep = serving_kw.get("num_replicas", 1)
    # per-replica window size under the RESOLVED per-phase topology
    # (decode_tp + prefill_tp when disaggregated — the same arithmetic
    # inference/server.py slices with)
    from megatron_tpu.serving.topology import devices_per_engine
    per = devices_per_engine(serving)
    devs = jax.devices()
    if per > 1:
        engines = [ServingEngine(gen, serving,
                                 devices=devs[i * per:(i + 1) * per])
                   for i in range(n_rep)]
    else:
        engines = [ServingEngine(gen, serving) for _ in range(n_rep)]
    if n_rep > 1:
        return (EngineRouter(engines, max_retries=2,
                             heartbeat_timeout_s=2.0,
                             probe_backoff_s=0.2),
                engines, gen)
    return engines[0], engines, gen


def _make_oracles(gen, model_kwargs: dict, serving_kw: dict,
                  adapters: dict, gen_v2=None, aux=None):
    """Per-weight-version oracle fns for invariants.check_token_exact:
    each maps a completed request -> the serial ground truth for its
    (prompt, n, seed, sampling) under its adapter's MERGED weights.
    Int8 pools get int8-kv serial generators (matched cache numerics).
    Grammar-constrained requests route to a lazily-built QUIET oracle
    engine instead (single slot, no faults, no speculation): the
    serial Generator has no mask seam, but a calm engine walking the
    same seeded chain is the ground truth the stormed engine must
    match. Engines built here are appended to `aux` for the caller to
    close."""
    import jax.numpy as jnp

    from megatron_tpu.inference.generation import (Generator,
                                                   SamplingParams)
    kv_dtype = (jnp.int8 if serving_kw.get("kv_dtype") == "int8"
                else jnp.bfloat16)
    rank, alpha = 4, 8.0
    aux = aux if aux is not None else []

    def _mk(base_gen):
        cache = {}

        def _gen_for(adapter_id):
            if adapter_id not in cache:
                if adapter_id is None:
                    params = base_gen.params
                else:
                    from megatron_tpu.training.lora import merge_lora
                    params = merge_lora(base_gen.params,
                                        adapters[adapter_id],
                                        base_gen.cfg, rank, alpha)
                cache[adapter_id] = Generator(params, base_gen.cfg,
                                              eos_id=-1, pad_id=0,
                                              kv_cache_dtype=kv_dtype)
            return cache[adapter_id]

        quiet = []

        def _quiet_engine():
            if not quiet:
                from megatron_tpu.config import ServingConfig
                from megatron_tpu.serving import ServingEngine
                skw = dict(num_slots=1, max_queue=64,
                           max_len=serving_kw.get("max_len", 128))
                if serving_kw.get("kv_dtype"):
                    skw["kv_dtype"] = serving_kw["kv_dtype"]
                if serving_kw.get("adapter_slots"):
                    skw["adapter_slots"] = serving_kw["adapter_slots"]
                eng = ServingEngine(
                    base_gen,
                    ServingConfig(**skw).validate(base_gen.cfg))
                for aid, factors in sorted(adapters.items()):
                    eng.register_adapter(aid, factors=factors,
                                         rank=rank, alpha=alpha)
                aux.append(eng)
                quiet.append(eng)
            return quiet[0]

        want_cache = {}

        def want(req):
            sp = req.sampling if hasattr(req, "sampling") \
                else req.spec["sampling"]
            seed = req.seed if hasattr(req, "seed") else req.spec["seed"]
            n = (req.max_new_tokens if hasattr(req, "max_new_tokens")
                 else req.spec["max_new_tokens"])
            aid = getattr(req, "adapter_id", None)
            if aid is None and hasattr(req, "spec"):
                aid = req.spec.get("adapter_id")
            rf = getattr(req, "response_format", None)
            if rf is None and hasattr(req, "spec"):
                rf = req.spec.get("response_format")
            key = (aid, tuple(req.prompt), n, seed,
                   (sp.temperature, sp.top_k, sp.top_p),
                   json.dumps(rf, sort_keys=True) if rf else None)
            if key not in want_cache:
                if rf is not None:
                    r2 = _quiet_engine().submit(
                        list(req.prompt), n, sp, seed=seed,
                        adapter_id=aid, response_format=rf)
                    # result() is prompt + generated, same shape the
                    # token-exact law compares against
                    toks, _ = r2.result(timeout=120.0)
                    want_cache[key] = list(toks)
                else:
                    t, lens, _ = _gen_for(aid).generate(
                        [list(req.prompt)], n,
                        sampling=SamplingParams(
                            temperature=sp.temperature,
                            top_k=sp.top_k, top_p=sp.top_p),
                        seed=seed)
                    want_cache[key] = t[0, :lens[0]].tolist()
            return want_cache[key]

        return want

    oracles = [_mk(gen)]
    if gen_v2 is not None:
        oracles.append(_mk(gen_v2))
    return oracles


def run_one(seed: int, require=(), n_requests: int = 12,
            new_tokens: int = 10, inject_violation: bool = False) -> dict:
    """One seeded conformance run. Returns the record; record["ok"] is
    the verdict and record["repro"] the one-line reproduction."""
    from megatron_tpu.resilience import use_fault_injector
    from megatron_tpu.serving import SamplingOptions

    rng = random.Random(seed)
    t0 = time.monotonic()
    # the FULL repro line: the rng stream's consumption depends on the
    # workload-size knobs too, so a repro without them replays a
    # different storm (and likely comes back green)
    repro = (f"python tools/chaos_mesh.py --seed {seed}"
             + (f" --require {','.join(require)}" if require else "")
             + f" --requests {n_requests} --new_tokens {new_tokens}")
    model_kwargs, serving_kw, rejections = sample_config(rng, require)
    specs, cancel_idx, stream_idx = build_workload(
        rng, serving_kw, n_requests, new_tokens, require=require)
    injector, fault_kinds = build_fault_injector(rng, serving_kw)
    actions = build_actions(rng, serving_kw, require)

    target, engines, gen = _build_target(model_kwargs, serving_kw)
    model = gen.cfg
    adapters = {}
    if serving_kw.get("adapter_slots"):
        adapters = cc.make_adapters(model, 2, rank=4)
        for aid, factors in sorted(adapters.items()):
            target.register_adapter(aid, factors=factors, rank=4,
                                    alpha=8.0)
    gen_v2 = root = d2 = None
    if "swap_good" in actions or "swap_corrupt" in actions:
        gen_v2 = cc.tiny_generator(model, seed=1)
        root = tempfile.mkdtemp(prefix="chaos_mesh_")
        d2 = cc.publish_checkpoint(root, model, gen_v2.params, 2)

    greedy = SamplingOptions(temperature=0.0)
    record = {
        "seed": seed, "require": list(require), "repro": repro,
        "config": {k: v for k, v in serving_kw.items() if v},
        "model": {k: v for k, v in model_kwargs.items()
                  if k != "compute"},
        "validate_rejections": len(rejections),
        "rejection_kinds": [r["rejected"] for r in rejections],
        "fault_kinds": fault_kinds, "actions": actions,
        # the seeded structured/fan-out draw (grammars regenerate from
        # the --seed repro line; recorded for log-line readability)
        "grammars": sorted({json.dumps(s["response_format"],
                                       sort_keys=True)
                            for s in specs if s.get("response_format")}),
        "fanout_specs": sum(1 for s in specs if s.get("best_of", 1) > 1),
    }
    reqs: list = []
    action_log = []
    stream_seen: list = []
    violations: list = []
    aux_engines: list = []  # quiet oracle engines (closed in finally)
    try:
        # warmup: compiles + the shed estimator's first sample, BEFORE
        # the injector arms (the fault schedule indexes steady steps)
        for eng in engines:
            eng.generate([3, 1, 4], 2, greedy, seed=0)
        with use_fault_injector(injector):
            for i, spec in enumerate(specs):
                try:
                    r = target.submit(**spec)
                    reqs.append(r)
                    if i == stream_idx:
                        # fan-out aggregates have no token stream of
                        # their own — follow sample 0, like the SSE
                        # layer's sample-major generator does
                        watch = (getattr(r, "children", None) or [r])[0]
                        threading.Thread(
                            target=_stream_watch,
                            args=(watch, stream_seen), daemon=True).start()
                    if i == cancel_idx:
                        time.sleep(0.01)
                        target.cancel(r)
                except Exception as e:  # noqa: BLE001 — typed rejections
                    action_log.append(
                        ("submit_rejected", type(e).__name__))
                time.sleep(0.005)
            for act in actions:
                time.sleep(0.05)
                action_log.append(
                    (act, _run_action(act, target, engines, rng, specs,
                                      reqs, d2, greedy)))
            # mid-storm LIGHT sweep: race-safe laws only
            mid = cc.invariant_sweep(target, strict=False)
            violations.extend(mid["violations"])
            # ride out the storm WITH the injector active (scheduled
            # step faults must be able to land mid-decode, not only
            # during the brief submission window); outcomes are
            # classified by the strict sweep below
            for r in reqs:
                try:
                    r.result(timeout=120.0)
                except Exception:  # noqa: BLE001 — typed-checked below
                    pass
        # post-storm STRICT sweep: resolve every future (typed
        # terminals / zero stranded), full accounting, oracle
        # exactness at every admitted weight version
        oracles = _make_oracles(gen, model_kwargs, serving_kw,
                                adapters, gen_v2=gen_v2,
                                aux=aux_engines)
        final = cc.invariant_sweep(target, reqs=reqs, oracles=oracles,
                                   strict=True, timeout=120.0)
        violations.extend(final["violations"])
        record["outcomes"] = final.get("outcomes", {})
        record["token_exact"] = final.get("token_exact", {})
        record["laws_checked"] = final.get("laws_checked", [])
        if inject_violation:
            # drop a terminal transition (the checker-not-vacuous pin):
            # the strict conservation law must now fail and report the
            # seed repro. Tamper verdicts stay SEPARATE from the real
            # storm's — an injected run must not mask a genuine
            # violation as "caught as intended"
            engines[0].metrics._counters["requests_completed"] -= 1
            tampered = cc.invariant_sweep(target, strict=True)
            record["injected_violation_caught"] = not tampered["ok"]
            record["injected_sweep_violations"] = (
                tampered["violations"]
                or ["[inject] tampered counter NOT caught — checker "
                    "is vacuous"])
    finally:
        try:
            target.close()
        except Exception:  # noqa: BLE001
            pass
        for eng in aux_engines:
            try:
                eng.close()
            except Exception:  # noqa: BLE001
                pass
    record.update({
        "faults_fired": [f"{k}:{d}" for k, d in injector.fired],
        "action_log": action_log,
        "stream_tokens_seen": len(stream_seen),
        "violations": violations,
        "wall_s": round(time.monotonic() - t0, 1),
        # an injected run still FAILS on genuine storm violations —
        # only the deliberately-tampered sweep's catch flips to "good"
        "ok": (not violations
               and (not inject_violation
                    or bool(record.get("injected_violation_caught")))),
    })
    if not record["ok"]:
        print(f"chaos_mesh: INVARIANT VIOLATION — repro: {repro}",
              file=sys.stderr)
        for v in violations:
            print(f"chaos_mesh:   {v}", file=sys.stderr)
    return record


def _stream_watch(req, seen: list):
    """Streaming consumer: follows tokens via wait_token the way the
    SSE layer does (exercises the per-token condition path under
    chaos); the committed stream it sees must be a prefix of the final
    result, which the oracle sweep already pins."""
    i = 0
    while req.wait_token(i, timeout=60.0):
        gen = list(req.generated)
        if len(gen) <= i:
            break  # terminal
        seen.append(gen[i])
        i += 1


def _run_action(act: str, target, engines, rng, specs, reqs, d2,
                greedy) -> str:
    """Execute one harness-level fault action; returns a short verdict
    string for the record (typed failures are EXPECTED outcomes)."""
    if act == "burst":
        n = 0
        for _ in range(6):
            spec = dict(rng.choice(specs))
            spec["seed"] = rng.randrange(1 << 20)
            try:
                reqs.append(target.submit(**spec))
                n += 1
            except Exception:  # noqa: BLE001 — 429/503 are the point
                pass
        return f"submitted {n}/6"
    if act == "kill_replica":
        engines[0].close()  # in-process analogue of an OOM-killed pod
        return "replica 0 closed"
    if act == "swap_corrupt":
        import glob
        import shutil
        # torn publish: corrupt a COPY so the later good swap still
        # has an intact checkpoint to apply
        bad = d2 + "_torn"
        if not os.path.isdir(bad):
            shutil.copytree(d2, bad)
            cc.corrupt_payload(bad)
        try:
            if hasattr(target, "rolling_upgrade"):
                target.rolling_upgrade(bad, swap_timeout_s=60)
            else:
                target.swap_weights(bad, timeout=60)
            return "corrupt swap APPLIED (gate failed!)"
        except Exception as e:  # noqa: BLE001 — typed refusal expected
            return f"refused typed: {type(e).__name__}"
    if act == "swap_good":
        try:
            if hasattr(target, "rolling_upgrade"):
                v = target.rolling_upgrade(d2, swap_timeout_s=60)
            else:
                v = target.swap_weights(d2, timeout=60)
            return f"swapped to {v.label}"
        except Exception as e:  # noqa: BLE001 — e.g. killed replica
            return f"not applied: {type(e).__name__}"
    return "unknown action"


# ---------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------
def run_smoke(n_requests: int, new_tokens: int) -> dict:
    runs = [run_one(seed, require, n_requests=n_requests,
                    new_tokens=new_tokens)
            for seed, require in SMOKE_SEEDS]
    ok = all(r["ok"] for r in runs)
    return {
        "metric": "chaos_mesh_configs_green",
        "value": sum(1 for r in runs if r["ok"]),
        "unit": (f"seeded configs with every invariant green "
                 f"(of {len(runs)}: adapters/disagg/live-swap/"
                 f"structured/fanout/asymmetric-phases/degrade "
                 f"corners)"),
        "vs_baseline": None,
        "completed": ok,
        "seed": SMOKE_SEEDS[0][0],
        "seeds": [list(s) for s in SMOKE_SEEDS],
        "runs": runs,
        "wall_s": round(sum(r["wall_s"] for r in runs), 1),
    }


def run_soak(minutes: float, start_seed: int, n_requests: int,
             new_tokens: int, require=()) -> dict:
    """Walk seeds until the budget expires; stop at the first
    violation (its repro line is the product). `require` biases every
    sampled config (and rides each run's repro line) — soaking a
    specific matrix corner."""
    deadline = time.monotonic() + minutes * 60.0
    runs, seed = [], start_seed
    first_bad = None
    while time.monotonic() < deadline:
        r = run_one(seed, require, n_requests=n_requests,
                    new_tokens=new_tokens)
        runs.append({k: r[k] for k in ("seed", "ok", "wall_s",
                                       "violations", "repro")})
        if not r["ok"]:
            first_bad = r
            break
        seed += 1
    ok = first_bad is None
    return {
        "metric": "chaos_mesh_soak_seeds_green",
        "value": sum(1 for r in runs if r["ok"]),
        "unit": (f"seeds green in {minutes:.1f} min soak "
                 f"(start --seed {start_seed}"
                 + (f", require {','.join(require)}" if require else "")
                 + ")"),
        "vs_baseline": None,
        "completed": ok,
        "seed": start_seed,
        "runs": runs,
        "first_violation": first_bad,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=None,
                    help="run ONE seeded conformance storm (the repro "
                         "knob: config + workload + fault schedule all "
                         "derive from it)")
    ap.add_argument("--require", type=str, default="",
                    help="comma-separated sampler biases (part of the "
                         "repro line): adapters, disagg, router, tp, "
                         "phases, swap, structured, fanout, degrade")
    ap.add_argument("--smoke", action="store_true",
                    help="fixed seed set for bench extras / CI: >= 6 "
                         "distinct configs covering adapters, "
                         "disaggregation, a live-weight swap, "
                         "structured output, n-best fan-out, and an "
                         "asymmetric per-phase (prefill_tp!=decode_tp) "
                         "disagg split")
    ap.add_argument("--minutes", type=float, default=None,
                    help="soak mode: walk seeds until the wall-clock "
                         "budget expires; stop at the first violation")
    ap.add_argument("--requests", type=int, default=12,
                    help="workload size per seed")
    ap.add_argument("--new_tokens", type=int, default=10,
                    help="max decode length per request")
    ap.add_argument("--inject_violation", action="store_true",
                    help="after the run, deliberately drop a terminal "
                         "transition and REQUIRE the checker to catch "
                         "it (exit 0 iff caught) — the checker-not-"
                         "vacuous pin")
    ap.add_argument("--out", type=str, default=None,
                    help="also write the JSON record here")
    args = ap.parse_args(argv)

    cc.force_host_devices(N_DEVICES)
    ensure_env_platform()
    require = tuple(t for t in args.require.split(",") if t)

    if args.minutes is not None:
        record = run_soak(args.minutes, args.seed or 0, args.requests,
                          args.new_tokens, require=require)
    elif args.smoke:
        record = run_smoke(args.requests, args.new_tokens)
    else:
        seed = args.seed if args.seed is not None else 0
        one = run_one(seed, require, n_requests=args.requests,
                      new_tokens=args.new_tokens,
                      inject_violation=args.inject_violation)
        record = {
            "metric": "chaos_mesh_invariants_green",
            "value": 1.0 if one["ok"] else 0.0,
            "unit": "seeded config x workload x fault schedule, all "
                    "system invariants",
            "vs_baseline": None,
            "completed": one["ok"],
            **one,
        }
    cc.emit_record(record, args.out, seed=record.get("seed", 0))
    return 0 if record["completed"] else 1


if __name__ == "__main__":
    sys.exit(main())
