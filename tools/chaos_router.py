"""Scripted front-door chaos drill: replica kill / wedge / host-tier
corruption over a REAL 2-replica router, measure that nothing strands
and nothing moves a token.

tools/chaos_serve.py proves one ENGINE survives its bad hour; this
tool proves the ROUTER in front of N engines survives a replica's bad
hour (docs/serving.md "Front door"). Three drills, each through a real
`EngineRouter` over two real `ServingEngine` replicas sharing one tiny
model:

1. **replica kill**: one replica dies mid-traffic (`close()` — the
   in-process analogue of the process being OOM-killed). Contract:
   zero accepted requests are lost — every future resolves, every
   COMPLETED request (requeued-and-retried ones included) is
   token-exact vs a serial single-replica run — the router ejects the
   dead replica (`router_failovers`), retries its work on the survivor
   (`router_retries`), `/healthz` reports DEGRADED (not down), and new
   submits keep succeeding.
2. **wedge one replica**: one replica's fetch seam stalls past its
   watchdog deadline mid-decode. Contract: the watchdog fails the
   wedged work, the router retries it on the survivor token-exact,
   and once the stalled replica's supervisor restarts it, the router
   re-admits it through a half-open canary — ending with BOTH
   replicas back in rotation.
3. **host-tier corruption**: a demoted prefix's host bytes are flipped.
   Contract: the checksum catches it (`host_tier_checksum_misses`),
   the request recomputes and stays token-exact — a corrupt demotion
   is a MISS, never wrong tokens — while an uncorrupted entry restores
   (`host_tier_hits`) token-exact.
4. **kill-the-prefill-half / kill-the-decode-half** (docs/serving.md
   "Sharded & disaggregated serving"): over a DISAGGREGATED 2-replica
   router — each replica a (prefill-group, decode-group) device pair —
   one replica permanently loses one HALF (its prefill or decode
   dispatch raises, the in-process analogue of that chip group dying).
   Contract: the half-dead replica's supervisor exhausts its restarts
   and trips the breaker, the router ejects the REPLICA (a pair with a
   dead half is a dead pair), every accepted request resolves
   token-exact on the surviving pair (token-exact resubmission covers
   a dead half exactly like a dead replica), `/healthz` reports
   DEGRADED (not down), and the survivor keeps handing off
   (`handoffs` still advances). Skipped with a note when the backend
   has < 4 devices (2 replicas x 2 groups); the CPU smoke forces a
   4-virtual-device host platform.
5. **kill-one-stage** (docs/serving.md "Pipeline-sharded serving"):
   over a router of 2 PIPELINE-SHARDED replicas — each a serving_pp=2
   stage chain of 2 devices — one replica permanently loses a layer
   STAGE (its stage-1 decode program raises, the in-process analogue
   of that stage's chip group dying). Contract: a chain with a dead
   stage is a dead chain — the supervisor's restart re-crashes (the
   compiled stage programs survive restarts, so the dead stage stays
   dead), the breaker trips, the router ejects the replica, every
   accepted request resolves token-exact on the surviving chain, and
   the survivor still runs STAGED (its per-stage trace counters stay
   [1, 1] — ejection caused zero recompiles). Skipped with a note
   when the backend has < 4 devices (2 replicas x 2 stages).

Every drill finishes with a system-wide `invariants.check_all` sweep
(serving/invariants.py): per-replica request conservation + KV
accounting + schema, plus the router-level degraded-not-down healthz
law — on top of each drill's own scenario assertions.

Emits ONE BENCH-style JSON record on stdout (and to --out), like
chaos_serve.py, so front-door regressions surface in the
`BENCH_*.json` extras. The scaffolding (tiny router builder, serial
oracle, outcome resolver) lives in tools/chaos_common.py, shared with
chaos_serve.py / chaos_upgrade.py / chaos_mesh.py.

  JAX_PLATFORMS=cpu python tools/chaos_router.py --smoke [--out FILE]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform
from tools.chaos_common import (emit_record, force_host_devices,
                                invariant_sweep,
                                resolve_exact as _resolve_exact,
                                serial_oracle as _serial_oracle,
                                tiny_router as _tiny_router)


def kill_drill(new_tokens: int) -> dict:
    from megatron_tpu.serving import SamplingOptions

    router, engines, gen = _tiny_router(dict(
        num_slots=2, max_queue=64, max_len=128,
        enable_prefix_cache=True, kv_block_size=16,
        block_native_attn=True))
    sampling = SamplingOptions(temperature=0.0)
    want = _serial_oracle(gen)
    try:
        # warmup both replicas (compiles + a health baseline)
        for eng in engines:
            eng.generate([3, 1, 4], 2, sampling, seed=0)
        reqs = []
        for i in range(8):
            p = [5 + i, 2, 7, 2, 7]
            reqs.append((router.submit(p, new_tokens, sampling, seed=i),
                         p, new_tokens))
        # wait until SOME work is actually decoding, then kill replica 0
        t_wait = time.monotonic() + 30
        while (engines[0].health()["active_slots"]
               + engines[1].health()["active_slots"] < 2
               and time.monotonic() < t_wait):
            time.sleep(0.002)
        engines[0].close()
        outcomes, exact = _resolve_exact(reqs, want)
        health = router.health()
        snap = router.aggregate_snapshot()
        # the front door still serves after losing a replica
        post = router.submit([9, 9, 8], 4, sampling, seed=99)
        post_toks, _ = post.result(timeout=60)
        post_exact = post_toks == want([9, 9, 8], 4)
        inv = invariant_sweep(router, [r for r, _, _ in reqs] + [post])
    finally:
        router.close()
    return {
        "submitted": len(reqs), "outcomes": outcomes,
        "completed_token_exact": exact,
        "router_failovers": int(snap["router_failovers"]),
        "router_retries": int(snap["router_retries"]),
        "health_state": health["state"],
        "healthz_ready": bool(health["healthy"]),
        "post_kill_serve_exact": post_exact,
        "invariants_ok": inv["ok"],
        "invariant_violations": inv["violations"],
        "ok": (outcomes["stranded"] == 0 and outcomes["error"] == 0
               and outcomes["ok"] == len(reqs) and exact
               and int(snap["router_failovers"]) >= 1
               and health["state"] == "degraded" and health["healthy"]
               and post_exact and inv["ok"]),
    }


def wedge_drill(new_tokens: int, timeout_s: float,
                stall_s: float) -> dict:
    from megatron_tpu.serving import SamplingOptions

    router, engines, gen = _tiny_router(
        dict(num_slots=1, max_queue=32, max_len=128,
             engine_step_timeout_s=timeout_s, max_engine_restarts=2),
        heartbeat_s=timeout_s)
    sampling = SamplingOptions(temperature=0.0)
    want = _serial_oracle(gen)
    try:
        for eng in engines:
            # warmup: compiles done AND each watchdog armed
            eng.generate([1, 2, 3], 2, sampling, seed=0)
        # wedge replica 0's sync seam: the next window stalls past the
        # watchdog deadline (the in-process analogue of a device hang)
        orig_fetch = engines[0]._fetch
        fired = []

        def stalling_fetch(tree):
            if not fired:
                fired.append(1)
                time.sleep(stall_s)
            return orig_fetch(tree)

        engines[0]._fetch = stalling_fetch
        reqs = []
        for i in range(4):
            p = [4 + i, 5, 4, 5]
            reqs.append((router.submit(p, new_tokens, sampling,
                                       seed=i), p, new_tokens))
        outcomes, exact = _resolve_exact(
            reqs, want, timeout=stall_s + timeout_s + 60)
        snap = router.aggregate_snapshot()
        # the wedged replica's supervisor restarts it; the router must
        # re-admit it via a half-open canary — poll until both UP
        recovered = False
        t_wait = time.monotonic() + stall_s + 30
        while time.monotonic() < t_wait:
            h = router.health()
            if h["state"] == "running" and h["replicas_up"] == 2:
                recovered = True
                break
            # traffic drives the canary: PROBING needs a request
            try:
                router.submit([8, 8], 2, sampling, seed=7).result(30)
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.05)
        health = router.health()
        inv = invariant_sweep(router, [r for r, _, _ in reqs])
    finally:
        router.close()
    return {
        "watchdog_timeout_s": timeout_s, "stall_s": stall_s,
        "submitted": len(reqs), "outcomes": outcomes,
        "completed_token_exact": exact,
        "router_failovers": int(snap["router_failovers"]),
        "router_retries": int(snap["router_retries"]),
        "wedged_fired": bool(fired),
        "recovered_both_up": recovered,
        "health_state": health["state"],
        "invariants_ok": inv["ok"],
        "invariant_violations": inv["violations"],
        "ok": (outcomes["stranded"] == 0 and outcomes["error"] == 0
               and exact and bool(fired) and recovered and inv["ok"]),
    }


def host_tier_drill(new_tokens: int) -> dict:
    from megatron_tpu.serving import SamplingOptions

    router, engines, gen = _tiny_router(dict(
        num_slots=2, max_queue=32, max_len=128,
        enable_prefix_cache=True, kv_block_size=16, block_native_attn=True,
        retained_slots=1,
        host_kv_bytes=1 << 22))
    sampling = SamplingOptions(temperature=0.0)
    want = _serial_oracle(gen)
    prefix = list(range(2, 20))  # > one 16-token block
    try:
        # warm ONLY replica 0 (drives affinity too: later prefix
        # traffic must route back to it via prefix_peek)
        engines[0].generate(prefix, new_tokens, sampling, seed=0)
        # churn retained entries so the prefix demotes to host RAM
        engines[0].generate([40, 41, 42], 2, sampling, seed=0)
        engines[0].generate([50, 51, 52], 2, sampling, seed=0)
        tier = engines[0]._host_tier
        demoted = len(tier) >= 1
        # phase 1 — clean restore through the ROUTER: affinity must
        # pick replica 0, the tier must hit, tokens must be exact
        p1 = prefix + [90, 91]
        affinity = router.prefix_peek(p1)
        t1, _ = router.submit(p1, new_tokens, sampling,
                              seed=1).result(60)
        exact1 = t1 == want(p1, new_tokens)
        snap1 = router.aggregate_snapshot()
        # phase 2 — churn the device-resident retained copies out
        # first (a device hit would legitimately win over the host
        # entry), then corrupt every demoted long entry and hit again:
        # checksum must catch it, the request must recompute exactly
        engines[0].generate([60, 61, 62], 2, sampling, seed=0)
        engines[0].generate([70, 71, 72], 2, sampling, seed=0)
        for ent in tier._entries.values():
            if ent.length >= 16:
                ent.arrays["k"].view("uint8").flat[0] ^= 0xFF
        p2 = prefix + [92, 93]
        t2, _ = router.submit(p2, new_tokens, sampling,
                              seed=2).result(60)
        exact2 = t2 == want(p2, new_tokens)
        snap2 = router.aggregate_snapshot()
        inv = invariant_sweep(router)
    finally:
        router.close()
    return {
        "demoted": demoted,
        "affinity_peek_tokens": int(affinity),
        "host_tier_demotions": int(snap2["host_tier_demotions"]),
        "host_tier_hits": int(snap2["host_tier_hits"]),
        "host_tier_checksum_misses":
            int(snap2["host_tier_checksum_misses"]),
        "clean_restore_exact": exact1,
        "corrupt_restore_exact": exact2,
        "invariants_ok": inv["ok"],
        "invariant_violations": inv["violations"],
        "ok": (demoted and affinity >= 16
               and int(snap1["host_tier_hits"]) >= 1 and exact1
               and int(snap2["host_tier_checksum_misses"]) >= 1
               and exact2 and inv["ok"]),
    }


def _tiny_disagg_router(new_tokens: int):
    """2-replica router over DISAGGREGATED engines: 4 devices, each
    replica a (prefill-group, decode-group) pair. A dead half keeps
    raising: one restart then the breaker — the replica must go
    hard-down fast so the router ejects it (max_engine_restarts=1)."""
    return _tiny_router(
        dict(num_slots=2, max_queue=64, max_len=128, kv_block_size=16,
             disaggregate_prefill=True, max_engine_restarts=1),
        heartbeat_s=2.0, probe_backoff_s=30.0, compute="bfloat16",
        devices_per=2)


def kill_half_drill(new_tokens: int, half: str) -> dict:
    """Kill one replica's prefill OR decode chip group mid-traffic
    and pin token-exact resubmission on the surviving pair."""
    import jax

    from megatron_tpu.serving import SamplingOptions

    if len(jax.devices()) < 4:
        return {"skipped": f"{len(jax.devices())} device(s) < 4 "
                           "(2 disaggregated replicas)", "ok": True}
    router, engines, gen = _tiny_disagg_router(new_tokens)
    sampling = SamplingOptions(temperature=0.0)
    want = _serial_oracle(gen)
    try:
        for eng in engines:
            eng.generate([3, 1, 4], 2, sampling, seed=0)

        def dead(*a, **k):
            raise RuntimeError(f"injected: {half} half down "
                               "(chip group lost)")

        # the half dies PERMANENTLY: every dispatch on it raises, so
        # the supervisor's restart re-crashes and the breaker trips
        if half == "prefill":
            engines[0]._chunk_fwd = dead
        else:
            engines[0]._decode = dead
        reqs = []
        for i in range(6):
            p = [5 + i, 2, 7, 2, 7]
            reqs.append((router.submit(p, new_tokens, sampling, seed=i),
                         p, new_tokens))
        outcomes, exact = _resolve_exact(reqs, want)
        health = router.health()
        snap = router.aggregate_snapshot()
        # the surviving PAIR still serves end-to-end — prefill group,
        # handoff, decode group
        post = router.submit([9, 9, 8], 4, sampling, seed=99)
        post_toks, _ = post.result(timeout=60)
        post_exact = post_toks == want([9, 9, 8], 4)
        snap_post = router.aggregate_snapshot()
        inv = invariant_sweep(router, [r for r, _, _ in reqs] + [post])
    finally:
        router.close()
    return {
        "half": half,
        "submitted": len(reqs), "outcomes": outcomes,
        "completed_token_exact": exact,
        "router_failovers": int(snap["router_failovers"]),
        "router_retries": int(snap["router_retries"]),
        "health_state": health["state"],
        "healthz_ready": bool(health["healthy"]),
        "post_kill_serve_exact": post_exact,
        "survivor_handoffs": int(snap_post["handoffs"]),
        "invariants_ok": inv["ok"],
        "invariant_violations": inv["violations"],
        "ok": (outcomes["stranded"] == 0 and outcomes["error"] == 0
               and outcomes["ok"] == len(reqs) and exact
               and int(snap["router_failovers"]) >= 1
               and health["state"] == "degraded" and health["healthy"]
               and post_exact and int(snap_post["handoffs"]) >= 1
               and inv["ok"]),
    }


def kill_stage_drill(new_tokens: int) -> dict:
    """Kill one replica's layer stage mid-traffic and pin token-exact
    resubmission on the surviving stage chain."""
    import jax

    from megatron_tpu.serving import SamplingOptions

    if len(jax.devices()) < 4:
        return {"skipped": f"{len(jax.devices())} device(s) < 4 "
                           "(2 pipeline-sharded replicas)", "ok": True}
    # each replica is a 2-stage chain (1 device per stage); a dead
    # stage keeps raising: one restart then the breaker
    router, engines, gen = _tiny_router(
        dict(num_slots=2, max_queue=64, max_len=128, kv_block_size=16,
             serving_pp=2, decode_tp=1, max_engine_restarts=1),
        heartbeat_s=2.0, probe_backoff_s=30.0, compute="bfloat16",
        devices_per=2)
    sampling = SamplingOptions(temperature=0.0)
    want = _serial_oracle(gen)
    try:
        for eng in engines:
            eng.generate([3, 1, 4], 2, sampling, seed=0)

        def dead(*a, **k):
            raise RuntimeError("injected: stage 1 down (stage chip "
                               "group lost)")

        # the stage dies PERMANENTLY: _restart_session keeps the
        # compiled stage programs (no retrace on restart), so the
        # patched program re-crashes the restarted loop and the
        # breaker trips
        engines[0]._pp_dec[1] = dead
        reqs = []
        for i in range(6):
            p = [5 + i, 2, 7, 2, 7]
            reqs.append((router.submit(p, new_tokens, sampling, seed=i),
                         p, new_tokens))
        outcomes, exact = _resolve_exact(reqs, want)
        health = router.health()
        snap = router.aggregate_snapshot()
        # the surviving CHAIN still serves end-to-end — embedding on
        # stage 0, activation crossing, head on stage 1
        post = router.submit([9, 9, 8], 4, sampling, seed=99)
        post_toks, _ = post.result(timeout=60)
        post_exact = post_toks == want([9, 9, 8], 4)
        survivor_traces = list(engines[1]._pp_decode_traces)
        survivor_staged = isinstance(engines[1].pool.caches, list)
        inv = invariant_sweep(router, [r for r, _, _ in reqs] + [post])
    finally:
        router.close()
    return {
        "submitted": len(reqs), "outcomes": outcomes,
        "completed_token_exact": exact,
        "router_failovers": int(snap["router_failovers"]),
        "router_retries": int(snap["router_retries"]),
        "health_state": health["state"],
        "healthz_ready": bool(health["healthy"]),
        "post_kill_serve_exact": post_exact,
        "survivor_stage_traces": survivor_traces,
        "survivor_staged": survivor_staged,
        "serving_pp_gauge": float(snap["serving_pp"]),
        "invariants_ok": inv["ok"],
        "invariant_violations": inv["violations"],
        "ok": (outcomes["stranded"] == 0 and outcomes["error"] == 0
               and outcomes["ok"] == len(reqs) and exact
               and int(snap["router_failovers"]) >= 1
               and health["state"] == "degraded" and health["healthy"]
               and post_exact and survivor_staged
               and survivor_traces == [1, 1]
               and float(snap["serving_pp"]) == 2.0
               and inv["ok"]),
    }


def run_chaos(new_tokens: int, timeout_s: float, stall_s: float) -> dict:
    t0 = time.monotonic()
    kill = kill_drill(new_tokens)
    wedge = wedge_drill(new_tokens, timeout_s, stall_s)
    host = host_tier_drill(new_tokens)
    kill_prefill = kill_half_drill(new_tokens, "prefill")
    kill_decode = kill_half_drill(new_tokens, "decode")
    kill_stage = kill_stage_drill(new_tokens)
    wall_s = time.monotonic() - t0
    ok = (kill["ok"] and wedge["ok"] and host["ok"]
          and kill_prefill["ok"] and kill_decode["ok"]
          and kill_stage["ok"])
    return {
        "metric": "router_chaos_failover_retries",
        "value": kill["router_retries"] + wedge["router_retries"],
        "unit": ("requeued-and-retried requests across kill+wedge "
                 "drills (all token-exact, zero lost)"),
        "vs_baseline": None,
        "completed": ok,
        "kill": kill,
        "wedge": wedge,
        "host_tier": host,
        "kill_prefill_half": kill_prefill,
        "kill_decode_half": kill_decode,
        "kill_stage": kill_stage,
        "wall_s": round(wall_s, 1),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny fixed scenario for bench extras / CI")
    ap.add_argument("--new_tokens", type=int, default=24,
                    help="decode length of the drill requests")
    ap.add_argument("--watchdog_s", type=float, default=1.0,
                    help="engine_step_timeout_s for the wedge drill")
    ap.add_argument("--stall_s", type=float, default=3.0,
                    help="injected fetch stall for the wedge drill")
    ap.add_argument("--out", type=str, default=None,
                    help="also write the JSON record here")
    args = ap.parse_args(argv)

    # the disaggregated kill-half drills need 4 devices (2 replicas x
    # 2 chip groups)
    force_host_devices(4)
    ensure_env_platform()
    if args.smoke:
        args.new_tokens, args.watchdog_s, args.stall_s = 12, 1.0, 2.5

    record = run_chaos(args.new_tokens, args.watchdog_s, args.stall_s)
    emit_record(record, args.out, seed=0)  # scripted: fixed workload
    return 0 if record["completed"] else 1


if __name__ == "__main__":
    sys.exit(main())
