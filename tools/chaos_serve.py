"""Scripted serving chaos drill: overload + injected engine faults
through a REAL engine, measure that nothing strands.

tests/test_serving.py proves each overload/failure path in isolation;
this tool composes them into ONE run the way a saturated replica's bad
hour would — offered load far above slot capacity, a NaN-poisoned
slot, a wedged decode iteration, a crash-looping step — and asserts
the engine's three survival contracts end-to-end:

1. **no stranded futures**: every submitted request resolves, as a
   completion or a TYPED error (shed/504/503/RuntimeError) — never a
   hang;
2. **hang recovery**: a wedged iteration is detected by the watchdog
   within `engine_step_timeout_s`, the in-flight futures fail, the
   supervisor restarts the loop, and a fresh probe request completes;
3. **crash-loop containment**: when every restart crashes again, the
   circuit breaker trips after `max_engine_restarts`, queued work
   resolves 503, `health()` reports unhealthy, and new submits raise
   EngineUnhealthyError.

Every drill finishes with a system-wide `invariants.check_all` sweep
(serving/invariants.py): the drill's own assertions pin its scenario,
the sweep pins the laws that must hold under ANY scenario (request
conservation, typed terminals, KV accounting, schema, healthz).

Emits ONE BENCH-style JSON record on stdout (and to --out), like
chaos_train.py, so hang-recovery regressions surface in the
`BENCH_*.json` extras. The scaffolding (tiny engine builders, serial
oracles, outcome resolvers) lives in tools/chaos_common.py, shared
with chaos_router.py / chaos_upgrade.py / chaos_mesh.py.

  JAX_PLATFORMS=cpu python tools/chaos_serve.py --smoke [--out FILE]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform
from tools.chaos_common import (emit_record, invariant_sweep,
                                make_adapters as _make_adapters,
                                pool_mode as _pool_mode,
                                resolve_all as _resolve_all,
                                tiny_engine as _tiny_engine)


def overload_drill(new_tokens: int, spec_k: int = 0,
                   pool_kwargs=None, n_adapters: int = 2) -> dict:
    """Offered load >> slot capacity with priorities, early shedding,
    preemption, one NaN-poisoned slot — speculative decoding when
    spec_k > 0, and `n_adapters` LoRA adapters INTERLEAVED through the
    traffic (multi-tenant serving under chaos). Contract: every
    submitted future resolves; sheds fail fast at submit; at least one
    preemption fires and every preempted request still resolves; and
    every request that COMPLETES — preempted-and-resumed included — is
    token-exact vs ITS OWN adapter's serial oracle (base weights with
    that adapter's A·B merged in): uncommitted draft state must drop
    cleanly, and preemption must save+restore the slot's adapter_idx
    with the rest of its state (a resumed victim decoding under the
    WRONG adapter would show up here as a token mismatch)."""
    from megatron_tpu.inference.generation import (Generator,
                                                   SamplingParams)
    from megatron_tpu.resilience import FaultInjector, use_fault_injector
    from megatron_tpu.serving import OverloadShedError, SamplingOptions

    rank, alpha = 4, 8.0
    eng, gen = _tiny_engine(dict(
        num_slots=2, max_queue=64, max_len=128, priority_levels=2,
        shed_on_overload=True, preemption=True, max_engine_restarts=2,
        speculative_k=spec_k, adapter_slots=n_adapters or 0,
        adapter_rank=rank, **(pool_kwargs or {})))
    adapters = _make_adapters(gen.cfg, n_adapters, rank)
    for aid, factors in sorted(adapters.items()):
        eng.register_adapter(aid, factors=factors, rank=rank,
                             alpha=alpha)
    # round-robin adapter assignment over [base, t-0, t-1, ...]
    cycle = [None] + sorted(adapters)

    def aid_for(i):
        return cycle[i % len(cycle)]

    # greedy: seed-independent, so the exactness oracle is one serial
    # generate per (adapter, prompt, n) — preemption/speculation must
    # not move a single token
    sampling = SamplingOptions(temperature=0.0)
    reqs, shed = [], 0
    # NaN-poison one active slot a few steps in: the non-finite guard
    # must fail exactly that REQUEST while the grid keeps decoding
    injector = FaultInjector(serve_nan_calls={6: 0})
    try:
        with use_fault_injector(injector):
            # warmup: compile + give the shed estimator its first
            # service-time sample (it never sheds blind)
            eng.generate([3, 1, 4], 2, sampling, seed=0)
            # wave 1 — capacity pressure: low-priority work fills both
            # slots and the queue (a repeated motif gives the
            # self-drafting matcher something to look up) ...
            for i in range(6):
                reqs.append((eng.submit([5 + i, 2, 7, 2, 7],
                                        new_tokens, sampling, seed=i,
                                        priority=0,
                                        adapter_id=aid_for(i)),
                             [5 + i, 2, 7, 2, 7], new_tokens,
                             aid_for(i)))
            # ... wait until low-priority work actually OCCUPIES the
            # slots (otherwise the priority queue simply serves the
            # high-priority wave first and nothing needs preempting) ...
            t_wait = time.monotonic() + 30
            while (eng.health()["active_slots"] < 2
                   and time.monotonic() < t_wait):
                time.sleep(0.002)
            # ... then high-priority arrivals preempt running slots
            # (preempt-mid-round: the victim's in-window draft state
            # is uncommitted by construction and must just vanish —
            # and its adapter pin must release/re-acquire cleanly)
            for i in range(3):
                n = max(new_tokens // 2, 2)
                reqs.append((eng.submit([9, 8 + i], n, sampling,
                                        seed=100 + i, priority=1,
                                        adapter_id=aid_for(i + 1)),
                             [9, 8 + i], n, aid_for(i + 1)))
            # wave 2 — hopeless deadlines: the estimator (fed by the
            # warmup completion) sheds these at SUBMIT time
            for i in range(16):
                try:
                    reqs.append((eng.submit([2, i + 1], new_tokens,
                                            sampling, seed=200 + i,
                                            deadline_s=0.001,
                                            adapter_id=aid_for(i)),
                                 [2, i + 1], new_tokens, aid_for(i)))
                except OverloadShedError:
                    shed += 1
            outcomes = _resolve_all([r for r, _, _, _ in reqs])
        snap = eng.metrics.snapshot()
        health = eng.health()
        # exactness sweep over everything that finished OK — each
        # request against ITS adapter's merged-weights serial oracle
        oracles = {None: gen}
        if n_adapters:
            from megatron_tpu.training.lora import merge_lora
            for aid, factors in adapters.items():
                oracles[aid] = Generator(
                    merge_lora(gen.params, factors, gen.cfg, rank,
                               alpha),
                    gen.cfg, eos_id=-1, pad_id=0)
        serial_cache, exact, checked = {}, True, 0
        adapter_checked = 0
        for r, prompt, n, aid in reqs:
            if r.state.value != "finished":
                continue
            key = (aid, tuple(prompt), n)
            if key not in serial_cache:
                t, lens, _ = oracles[aid].generate(
                    [prompt], n,
                    sampling=SamplingParams(temperature=0.0))
                serial_cache[key] = t[0, :lens[0]].tolist()
            checked += 1
            if aid is not None:
                adapter_checked += 1
            if r.prompt + r.generated != serial_cache[key]:
                exact = False
        # system-wide law sweep (serving/invariants.py): conservation,
        # typed terminals, KV accounting, schema, healthz — on top of
        # the drill's own scenario assertions
        inv = invariant_sweep(eng, [r for r, _, _, _ in reqs])
    finally:
        eng.close()
    fired = {k: sum(1 for f, _ in injector.fired if f == k)
             for k in ("serve_nan",)}
    return {
        "submitted": len(reqs), "shed_at_submit": shed,
        "outcomes": outcomes,
        "preemptions": int(snap["preemptions"]),
        "requests_shed": int(snap["requests_shed"]),
        "nonfinite_logit_fails": int(snap["nonfinite_logit_fails"]),
        "nan_faults_fired": fired["serve_nan"],
        "speculative_k": spec_k,
        "spec_rounds": int(snap["spec_rounds"]),
        "draft_tokens": int(snap["draft_tokens"]),
        "adapters": n_adapters,
        "adapter_loads": int(snap["adapter_loads"]),
        "adapter_rows_checked": adapter_checked,
        "completed_token_exact": exact,
        "completed_checked": checked,
        "healthy_after": bool(health["healthy"]),
        "invariants_ok": inv["ok"],
        "invariant_violations": inv["violations"],
        "ok": (outcomes["stranded"] == 0
               and shed + int(snap["requests_shed"]) >= 1
               and int(snap["preemptions"]) >= 1
               and int(snap["nonfinite_logit_fails"])
               >= fired["serve_nan"] > 0
               and exact and checked >= 1
               and (spec_k == 0 or int(snap["spec_rounds"]) >= 1)
               and (n_adapters == 0
                    or (int(snap["adapter_loads"]) >= 1
                        and adapter_checked >= 1))
               and health["healthy"] and inv["ok"]),
    }


def hang_drill(timeout_s: float, stall_s: float, spec_k: int = 0,
               pool_kwargs=None) -> dict:
    """A wedged decode iteration: the watchdog must fail the in-flight
    futures within its deadline and the supervisor must restart the
    loop once the stalled dispatch returns — measured as the wall time
    from the hang-victim's failure to a fresh probe completing. With
    spec_k > 0 the wedged iteration is a speculative window: the
    restart must drop its uncommitted draft state with the rest of the
    device state, and the greedy probe must come back token-exact."""
    from megatron_tpu.inference.generation import SamplingParams
    from megatron_tpu.resilience import FaultInjector, use_fault_injector
    from megatron_tpu.serving import SamplingOptions

    eng, gen = _tiny_engine(dict(
        num_slots=1, max_queue=16, max_len=128,
        engine_step_timeout_s=timeout_s, max_engine_restarts=2,
        speculative_k=spec_k, **(pool_kwargs or {})))
    sampling = SamplingOptions(temperature=0.0)
    try:
        # warmup: compiles done AND the watchdog armed (it arms only
        # after the first completed iteration)
        eng.generate([1, 2, 3], 2, sampling, seed=0)
        injector = FaultInjector(serve_delay_calls={1: stall_s})
        with use_fault_injector(injector):
            victim = eng.submit([4, 5, 4, 5], 8, sampling, seed=1)
            t0 = time.monotonic()
            try:
                victim.result(timeout=stall_s + timeout_s + 30)
                victim_failed = False
            except TimeoutError:
                victim_failed = False
            except Exception:  # noqa: BLE001 — the watchdog failed it
                victim_failed = True
            detect_s = time.monotonic() - t0
            # the supervisor restarts after the stalled dispatch
            # returns; a fresh probe must then complete normally
            probe = eng.submit([6, 7, 6, 7], 4, sampling, seed=2)
            probe_toks, _ = probe.result(timeout=60)
            recovery_s = time.monotonic() - t0
        t, lens, _ = gen.generate([[6, 7, 6, 7]], 4,
                                  sampling=SamplingParams(
                                      temperature=0.0))
        probe_exact = probe_toks == t[0, :lens[0]].tolist()
        health = eng.health()
        snap = eng.metrics.snapshot()
        inv = invariant_sweep(eng, [victim, probe])
    finally:
        eng.close()
    return {
        "watchdog_timeout_s": timeout_s, "stall_s": stall_s,
        "victim_failed_typed": victim_failed,
        "detect_s": round(detect_s, 3),
        "recovery_s": round(recovery_s, 3),
        "engine_restarts": int(snap["engine_restarts"]),
        "speculative_k": spec_k,
        "probe_token_exact": probe_exact,
        "healthy_after": bool(health["healthy"]),
        "invariants_ok": inv["ok"],
        "invariant_violations": inv["violations"],
        "ok": (victim_failed and inv["ok"]
               and int(snap["engine_restarts"]) >= 1
               # the victim must fail by watchdog detection (deadline +
               # poll slack), i.e. strictly before the stalled dispatch
               # itself would have returned and failed it anyway
               and detect_s < stall_s + timeout_s
               and probe_exact
               and health["healthy"] and health["state"] == "running"),
    }


def crash_loop_drill(spec_k: int = 0, pool_kwargs=None) -> dict:
    """Every step crashes: the supervisor restarts max_engine_restarts
    times, then trips the circuit breaker. Everything in flight or
    queued resolves with a typed error, health() reports unhealthy,
    and new submits raise EngineUnhealthyError (the server's 503).
    With spec_k > 0 the crashing step is a speculative window — the
    restart/breaker path must behave identically (draft state is
    host-side and dies with the window)."""
    from megatron_tpu.resilience import FaultInjector, use_fault_injector
    from megatron_tpu.serving import EngineUnhealthyError, SamplingOptions

    eng, _ = _tiny_engine(dict(
        num_slots=1, max_queue=16, max_len=128, max_engine_restarts=1,
        speculative_k=spec_k, **(pool_kwargs or {})))
    sampling = SamplingOptions(temperature=1.0)
    try:
        eng.generate([1, 2], 2, sampling, seed=0)  # warmup
        injector = FaultInjector(
            serve_crash_calls=set(range(1, 64)))
        with use_fault_injector(injector):
            reqs = [eng.submit([3 + i], 4, sampling, seed=i)
                    for i in range(4)]
            outcomes = _resolve_all(reqs, timeout=60)
        health = eng.health()
        snap = eng.metrics.snapshot()
        try:
            eng.submit([9], 2, sampling, seed=99)
            submit_rejected_503 = False
        except EngineUnhealthyError:
            submit_rejected_503 = True
        # the laws hold on a BROKEN engine too: every request terminal
        # exactly once, healthz consistently unhealthy, schema stable
        inv = invariant_sweep(eng, reqs)
    finally:
        eng.close()
    return {
        "submitted": 4, "outcomes": outcomes,
        "engine_restarts": int(snap["engine_restarts"]),
        "breaker_open": bool(health["circuit_breaker_open"]),
        "state": health["state"],
        "submit_rejected_503": submit_rejected_503,
        "invariants_ok": inv["ok"],
        "invariant_violations": inv["violations"],
        "ok": (outcomes["stranded"] == 0 and outcomes["ok"] == 0
               and int(snap["engine_restarts"]) == 1
               and health["circuit_breaker_open"]
               and not health["healthy"]
               and submit_rejected_503 and inv["ok"]),
    }


def run_chaos(new_tokens: int, timeout_s: float, stall_s: float,
              spec_k: int = 0, block: int = 16,
              block_native: bool = True, n_adapters: int = 2) -> dict:
    t0 = time.monotonic()
    pool_kwargs = _pool_mode(block, block_native)
    overload = overload_drill(new_tokens, spec_k, pool_kwargs,
                              n_adapters=n_adapters)
    hang = hang_drill(timeout_s, stall_s, spec_k, pool_kwargs)
    crash = crash_loop_drill(spec_k, pool_kwargs)
    wall_s = time.monotonic() - t0
    ok = overload["ok"] and hang["ok"] and crash["ok"]
    return {
        "metric": "serve_chaos_hang_recovery_s",
        "value": hang["recovery_s"],
        "unit": (f"s hang-detect->restart->serve (watchdog "
                 f"{timeout_s}s, stall {stall_s}s)"),
        "vs_baseline": None,
        "completed": ok,
        "speculative_k": spec_k,
        "kv_block_size": block or None,
        "block_native_attn": bool(block and block_native),
        "adapters": n_adapters,
        "overload": overload,
        "hang": hang,
        "crash_loop": crash,
        "wall_s": round(wall_s, 1),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny fixed scenario for bench extras / CI")
    ap.add_argument("--new_tokens", type=int, default=24,
                    help="decode length of the overload wave's requests")
    ap.add_argument("--watchdog_s", type=float, default=1.0,
                    help="engine_step_timeout_s for the hang drill")
    ap.add_argument("--stall_s", type=float, default=3.0,
                    help="injected serve_delay for the hang drill")
    ap.add_argument("--speculative_k", type=int, default=4,
                    help="run every drill with speculative decoding at "
                         "this k (0 = the pre-speculative drills): "
                         "preempt-mid-round / crash-restart / "
                         "watchdog-hang must drop uncommitted draft "
                         "state cleanly — resumed requests token-exact, "
                         "no stranded futures")
    ap.add_argument("--adapters", type=int, default=2,
                    help="run the overload drill with this many LoRA "
                         "adapters interleaved through the traffic "
                         "(multi-tenant serving under chaos): every "
                         "completed request pins token-exact against "
                         "its OWN adapter's merged-weights serial "
                         "oracle — preempt/resume must save+restore "
                         "the slot's adapter binding (0 = adapterless "
                         "drills)")
    ap.add_argument("--kv_block_size", type=int, default=16,
                    help="run every drill on the BLOCK-granular pool "
                         "at this block size — the production layout "
                         "gets the chaos coverage, not only the "
                         "whole-region fallback (0 = whole-region)")
    ap.add_argument("--no_block_native", action="store_true",
                    help="keep the resolve/scatter bracket instead of "
                         "the block-native attention kernel (the "
                         "kernel is on by default wherever legal)")
    ap.add_argument("--out", type=str, default=None,
                    help="also write the JSON record here")
    args = ap.parse_args(argv)

    ensure_env_platform()
    if args.smoke:
        args.new_tokens, args.watchdog_s, args.stall_s = 16, 1.0, 2.5

    record = run_chaos(args.new_tokens, args.watchdog_s, args.stall_s,
                       args.speculative_k, args.kv_block_size,
                       not args.no_block_native, args.adapters)
    emit_record(record, args.out, seed=0)  # scripted: fixed workload
    return 0 if record["completed"] else 1


if __name__ == "__main__":
    sys.exit(main())
