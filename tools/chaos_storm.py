#!/usr/bin/env python
"""chaos_storm: seeded SLO-storm conformance for the brownout ladder.

chaos_mesh answers "does a random legal CONFIG survive a random fault
storm?". This tool answers the orthogonal question: "does a fixed
config survive a random LOAD storm within its SLOs — and degrade the
way the ladder promises while it does?". One seed derives a
trace-driven workload (bursty/Poisson arrivals, multi-turn sessions,
adapter skew, prompt-length and decode-length mixtures) which is
replayed at several OFFERED-LOAD multiples of the engine's measured
sustainable rate (the `--arms` sweep, default 0.5x/1x/2x), against an
engine running the full degradation ladder (`degrade_ladder=4`,
docs/serving.md "Overload, degradation & SLO conformance").

Laws checked per seed (serving/invariants.py perf laws 8-11, plus the
structural sweep):

  - slo_bounds      TTFT bounded at the 1x (target-utilization) arm,
                    per-request mean ITL p99 bounded across ALL arms.
                    Bounds derive from a serial calibration phase, with
                    generous slack: CPU jitter is noise, a stalled loop
                    is a regression.
  - goodput_floor   completed-token goodput stays above a floor of the
                    generated total even while the 2x arm sheds.
  - shed_monotone   shed fraction is non-decreasing in offered load
                    across arms (a harness tolerance absorbs run-to-run
                    scheduling noise).
  - degrade_revert  the polled brownout-level series stays within
                    [0, max_level], RISES under the 2x arm, and is
                    fully back at level 0 after the storm drains —
                    brownout, not blackout, and no sticky degradation.
  - zero stranded   every submitted-and-admitted future resolves.
  - token_exact     every COMPLETED request matches the serial oracle
                    for its OWN effective config: a level-2 clamp
                    rewrites max_new_tokens/best_of at admission, so
                    the oracle keys off the request object's fields,
                    not the caller's — degraded output is shorter,
                    never different.

`--inject_slo_regression` arms a real serve_delay fault (an 8s engine
loop stall mid-storm) and REQUIRES the SLO law to catch it, printing
the one-line seed repro — the checker-not-vacuous pin for the perf
laws, same contract as chaos_mesh's `--inject_violation`.

Every record carries the seed + full repro line; `--smoke` runs the
fixed seed set wired into bench.py extras and the slow test tier.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform  # noqa: E402
from tools import chaos_common as cc  # noqa: E402

N_DEVICES = 4

# smoke = the bench-extras / slow-tier gate: plain greedy storm, a
# speculative engine (exercises the level-1 spec-off rung bit-exactly),
# and an adapter-skewed multi-tenant storm (fp32 activations per the
# oracle-numerics rule in chaos_common.auto_compute_dtype)
SMOKE_SEEDS = [(17, ()), (29, ("spec",)), (41, ("adapters",))]

DEFAULT_ARMS = (0.5, 1.0, 2.0)
SHED_TOLERANCE = 0.15     # adjacent-arm shed-fraction noise allowance
GOODPUT_FLOOR = 0.5
LORA_RANK, LORA_ALPHA = 4, 8.0


# ---------------------------------------------------------------------
# seeded config + workload trace
# ---------------------------------------------------------------------
def sample_config(rng: random.Random, require=()):
    """Serving kwargs for the stormed engine. Unlike chaos_mesh this is
    mostly FIXED — the storm varies load, not topology — but the spec /
    adapter axes stay seeded so the ladder's level-1 and level-2 rungs
    meet real traffic. Thresholds are lowered from the production
    defaults so a 2x arm on the tiny CPU model actually climbs the
    ladder within a smoke-sized trace."""
    kw = {
        "num_slots": 2,
        "max_queue": rng.choice([6, 8]),
        "max_len": 96,
        "shed_on_overload": True,
        "priority_levels": 2,
        "degrade_ladder": 4,
        "degrade_raise_at": (0.25, 0.5, 1.0, 2.0),
        "degrade_hysteresis": 0.5,
        "degrade_dwell_up": 2,
        "degrade_dwell_down": 4,
        "degrade_max_new_tokens": 6,
        # engine-side SLO counters: generous wall-clock bounds (the
        # harness-side calibrated bounds are the real law; these pin
        # that the /metrics counters wire end to end)
        "slo_ttft_ms": 30_000.0,
        "slo_itl_p99_ms": 30_000.0,
    }
    if "spec" in require or (not require and rng.random() < 0.3):
        kw["speculative_k"] = 2
    if "adapters" in require:
        kw["adapter_slots"] = 2
    return kw


def build_trace(rng: random.Random, serving_kw: dict, n_requests: int,
                new_tokens: int, adapters=()):
    """The seeded workload trace: a list of spec dicts replayed (with
    arm-scaled interarrival gaps) by every arm. Axes: bursty arrivals
    (burst_every/burst_len), prompt-length mixture, decode-length
    mixture, priority skew (70% best-effort — the level-3 shed class),
    adapter skew (80/20 toward one hot tenant), a multi-turn session
    fraction (follow-ups extend an earlier request's prompt with its
    completion), and a small n-best fan-out fraction (meets the level-2
    best_of clamp)."""
    greedy_only = bool(serving_kw.get("speculative_k"))
    max_len = serving_kw["max_len"]
    adapters = list(adapters)
    specs = []
    for i in range(n_requests):
        long_prompt = rng.random() < 0.3
        plen = rng.randint(16, 28) if long_prompt else rng.randint(4, 8)
        spec = {
            "prompt": [rng.randrange(1, 128) for _ in range(plen)],
            "max_new_tokens": (new_tokens if rng.random() < 0.7
                               else max(2, new_tokens // 2)),
            "seed": rng.randrange(1 << 16),
            "priority": 1 if rng.random() < 0.3 else 0,
            "adapter_id": None,
            "n": 1, "best_of": None,
            "session_of": None,
            # seeded-stochastic rows are oracle-exact EXCEPT under
            # speculation (chaos_common.serial_oracle contract), so a
            # spec engine storms greedy
            "temperature": (0.0 if greedy_only or rng.random() < 0.6
                            else 0.8),
        }
        if adapters and rng.random() < 0.5:
            # 80/20 skew: one hot tenant, a cold tail
            spec["adapter_id"] = (adapters[0] if rng.random() < 0.8
                                  else rng.choice(adapters))
        if i >= 2 and rng.random() < 0.25:
            spec["session_of"] = rng.randrange(i)  # multi-turn follow-up
        elif spec["priority"] and rng.random() < 0.3:
            spec["n"], spec["best_of"] = 1, 2     # small n-best fan-out
        # admission guard: prompt + decode must fit the pool row even
        # after a session follow-up extends the prompt
        spec["prompt"] = spec["prompt"][:max_len - new_tokens - 16]
        specs.append(spec)
    # arrival schedule in UNITS of the sustainable interarrival gap:
    # Poisson (exponential gaps) with periodic bursts arriving back to
    # back — the p99-ITL-under-burst law needs real bursts
    gaps, burst_every, burst_len = [], rng.randint(5, 8), rng.randint(3, 4)
    for i in range(n_requests):
        in_burst = (i % burst_every) < burst_len and i > 0
        gaps.append(0.0 if in_burst else rng.expovariate(1.0))
    return specs, gaps


# ---------------------------------------------------------------------
# serial oracle (effective-config keyed)
# ---------------------------------------------------------------------
def make_oracle(gen, adapter_factors: dict):
    """`fn(req) -> expected tokens` for invariants.check_token_exact.
    Keys the serial reference off the REQUEST's own fields — after a
    level-2 clamp those are the effective (rewritten) max_new_tokens
    and fan-out, which is exactly the contract: degraded completions
    are token-exact vs their own effective config's serial run."""
    from megatron_tpu.inference.generation import (Generator,
                                                   SamplingParams)
    gens, cache = {None: gen}, {}

    def _gen_for(adapter_id):
        if adapter_id not in gens:
            from megatron_tpu.training.lora import merge_lora
            params = merge_lora(gen.params, adapter_factors[adapter_id],
                                gen.cfg, LORA_RANK, LORA_ALPHA)
            gens[adapter_id] = Generator(params, gen.cfg,
                                         eos_id=-1, pad_id=0)
        return gens[adapter_id]

    def want(req):
        sp = req.sampling
        key = (req.adapter_id, tuple(req.prompt), req.max_new_tokens,
               req.seed, (sp.temperature, sp.top_k, sp.top_p))
        if key not in cache:
            t, lens, _ = _gen_for(req.adapter_id).generate(
                [list(req.prompt)], req.max_new_tokens,
                sampling=SamplingParams(temperature=sp.temperature,
                                        top_k=sp.top_k, top_p=sp.top_p),
                seed=req.seed)
            cache[key] = t[0, :lens[0]].tolist()
        return cache[key]

    return want


# ---------------------------------------------------------------------
# storm driver
# ---------------------------------------------------------------------
class _LevelPoller:
    """Background sampler of health()["degrade_level"] — the series the
    degrade_revert law judges. 10ms cadence is well under the dwell
    window, so no transition can slip between samples unseen."""

    def __init__(self, engine, period_s: float = 0.01):
        self.levels, self._stop = [], threading.Event()
        self._t = threading.Thread(
            target=self._run, args=(engine, period_s), daemon=True)

    def _run(self, engine, period_s):
        while not self._stop.is_set():
            self.levels.append(int(engine.health()["degrade_level"]))
            time.sleep(period_s)

    def __enter__(self):
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._t.join(timeout=5.0)


def calibrate(engine, rng: random.Random, new_tokens: int) -> float:
    """Measured per-request service time (s) on the quiet engine —
    warmup (compile) excluded. The sustainable interarrival gap at
    1x offered load is service_time / num_slots."""
    warm = engine.submit([1, 2, 3], new_tokens)
    warm.result(timeout=120.0)
    times = []
    for _ in range(3):
        t0 = time.monotonic()
        r = engine.submit([rng.randrange(1, 128) for _ in range(6)],
                          new_tokens, seed=rng.randrange(1 << 16))
        r.result(timeout=120.0)
        times.append(time.monotonic() - t0)
    return max(sum(times) / len(times), 1e-3)


def run_arm(engine, specs, gaps, mult: float, base_gap_s: float):
    """Replay the trace at `mult` x the sustainable rate. Returns
    (tracked GenRequests, per-arm stats). Submit-time 429s (queue full
    / brownout shed) are the SHED bucket; Retry-After hints are checked
    >= 1s inline — the herd-clamp satellite, enforced where the storm
    actually sheds."""
    from megatron_tpu.serving import SamplingOptions
    from megatron_tpu.serving.scheduler import QueueFullError
    tracked, stats = [], {"mult": mult, "submitted": 0, "shed": 0,
                          "bad_retry_after": 0, "stranded": 0,
                          "completed": 0, "failed": 0,
                          "ttft_ms": [], "itl_ms": []}
    done_prompts = {}   # trace index -> (prompt, generated) for sessions
    t_next = time.monotonic()
    for i, (spec, gap) in enumerate(zip(specs, gaps)):
        t_next += gap * base_gap_s / max(mult, 1e-6)
        time.sleep(max(0.0, t_next - time.monotonic()))
        prompt = list(spec["prompt"])
        parent = done_prompts.get(spec["session_of"])
        if parent is not None:
            # multi-turn: the follow-up turn carries the whole prior
            # exchange (prompt + completion) plus the new user tokens
            prompt = (parent[0] + parent[1])[-24:] + prompt[:6]
        stats["submitted"] += 1
        try:
            r = engine.submit(
                prompt, spec["max_new_tokens"],
                SamplingOptions(temperature=spec["temperature"]),
                seed=spec["seed"], priority=spec["priority"],
                adapter_id=spec["adapter_id"],
                n=spec["n"], best_of=spec["best_of"])
        except QueueFullError as e:   # OverloadShedError subclasses it
            stats["shed"] += 1
            if e.retry_after is not None and e.retry_after < 1:
                stats["bad_retry_after"] += 1
            continue
        tracked.append((i, r))
    for i, r in tracked:
        try:
            r.result(timeout=120.0)
        except TimeoutError:
            stats["stranded"] += 1
            continue
        except Exception:  # noqa: BLE001 — typed-enough: it RESOLVED
            stats["failed"] += 1
            continue
        stats["completed"] += 1
        children = getattr(r, "children", None) or [r]
        done_prompts[i] = (list(children[0].prompt),
                           list(children[0].generated))
        for c in children:
            if c.ttft is not None:
                stats["ttft_ms"].append(c.ttft * 1e3)
            gen = len(c.generated)
            if gen > 1 and c.finish_time and c.first_token_time:
                stats["itl_ms"].append(
                    (c.finish_time - c.first_token_time) * 1e3
                    / (gen - 1))
    stats["shed_frac"] = stats["shed"] / max(stats["submitted"], 1)
    return [r for _, r in tracked], stats


def run_one(seed: int, require=(), n_requests: int = 10,
            new_tokens: int = 8, arms=DEFAULT_ARMS,
            inject_slo_regression: bool = False) -> dict:
    """One seeded storm across all arms. record["ok"] is the verdict,
    record["repro"] the one-line reproduction."""
    from megatron_tpu.resilience import FaultInjector, use_fault_injector
    from megatron_tpu.serving import invariants

    rng = random.Random(seed)
    t0 = time.monotonic()
    arms = tuple(sorted(arms))
    repro = (f"python tools/chaos_storm.py --seed {seed}"
             + (f" --require {','.join(require)}" if require else "")
             + f" --requests {n_requests} --new_tokens {new_tokens}"
             + f" --arms {','.join(str(a) for a in arms)}"
             + (" --inject_slo_regression" if inject_slo_regression
                else ""))
    serving_kw = sample_config(rng, require)
    record = {"metric": "storm_requests_conformant",
              "unit": ("completed requests, every perf + structural "
                       "law green"),
              "seed": seed, "repro": repro, "require": list(require),
              "config": {k: v for k, v in serving_kw.items()
                         if k not in ("slo_ttft_ms", "slo_itl_p99_ms")},
              "completed": False, "ok": False, "violations": []}

    engine, gen = cc.tiny_engine(serving_kw)
    adapter_factors = {}
    try:
        if serving_kw.get("adapter_slots"):
            adapter_factors = cc.make_adapters(gen.cfg, 2, rank=LORA_RANK)
            for aid, factors in sorted(adapter_factors.items()):
                engine.register_adapter(aid, factors=factors,
                                        rank=LORA_RANK, alpha=LORA_ALPHA)
        specs, gaps = build_trace(rng, serving_kw, n_requests,
                                  new_tokens,
                                  adapters=sorted(adapter_factors))
        svc_s = calibrate(engine, rng, new_tokens)
        base_gap_s = svc_s / serving_kw["num_slots"]
        # calibrated bounds, generous: CPU scheduling jitter must not
        # page anyone; a wedged loop / O(n) regression must
        ttft_bound_ms = 30 * svc_s * 1e3 + 5_000
        itl_bound_ms = 50 * svc_s * 1e3 / max(new_tokens, 1) + 2_000
        injector = None
        if inject_slo_regression:
            # a real mid-storm regression: stall the engine loop 8s
            # early in the first arm (the injector's serve-step counter
            # starts at install, after calibration). Everything queued
            # behind the stall blows a tightened TTFT bound — the law
            # MUST catch it (checker-not-vacuous)
            injector = FaultInjector(serve_delay_calls={5: 8.0})

        all_reqs, arm_stats = [], []
        with _LevelPoller(engine) as poller:
            ctx = (use_fault_injector(injector) if injector is not None
                   else _null_ctx())
            with ctx:
                for mult in arms:
                    reqs, stats = run_arm(engine, specs, gaps, mult,
                                          base_gap_s)
                    all_reqs.extend(reqs)
                    arm_stats.append(stats)
            # drain: the revert law needs the ladder walked back to 0,
            # which the idle engine loop does on dwell_down evaluations
            deadline = time.monotonic() + 30.0
            while (engine.health()["degrade_level"]
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            time.sleep(0.1)   # a final settled sample for the series

        # ---- laws ---------------------------------------------------
        sweep = cc.invariant_sweep(engine, reqs=all_reqs,
                                   oracles=[make_oracle(gen,
                                                        adapter_factors)],
                                   strict=True, timeout=120.0)
        violations = list(sweep.get("violations", []))
        stranded = sum(s["stranded"] for s in arm_stats)
        if stranded:
            violations.append(f"[stranded] {stranded} futures never "
                              "resolved")
        bad_ra = sum(s["bad_retry_after"] for s in arm_stats)
        if bad_ra:
            violations.append(f"[retry_after] {bad_ra} shed responses "
                              "hinted Retry-After < 1s")

        if inject_slo_regression:
            # the stall fires in the FIRST arm, so the law judges the
            # whole storm's TTFT series against the tightened bound
            samples = {"ttft_all": [v for s in arm_stats
                                    for v in s["ttft_ms"]]}
            bounds = {"ttft_all": (0.9, 4_000.0)}
        else:
            target = next((s for s in arm_stats if s["mult"] == 1.0),
                          arm_stats[len(arm_stats) // 2])
            samples = {"ttft_1x": target["ttft_ms"],
                       "itl_all": [v for s in arm_stats
                                   for v in s["itl_ms"]]}
            bounds = {"ttft_1x": (0.95, ttft_bound_ms),
                      "itl_all": (0.99, itl_bound_ms)}
        slo_violated = False
        try:
            record["slo"] = invariants.check_slo_bounds(samples, bounds)
        except invariants.InvariantViolation as e:
            slo_violated = True
            if not inject_slo_regression:
                violations.append(str(e))
        if not inject_slo_regression:
            # load-shape laws only hold for an UNfaulted storm (the
            # injected 8s stall legitimately skews arm-0 shedding)
            for check, kwargs in (
                    (invariants.check_shed_monotone,
                     {"arms": [(s["mult"], s["shed_frac"])
                               for s in arm_stats],
                      "tolerance": SHED_TOLERANCE}),
                    (invariants.check_goodput_floor,
                     {"snapshot": engine.metrics.snapshot(),
                      "floor": GOODPUT_FLOOR}),
                    (invariants.check_degrade_revert,
                     {"levels": poller.levels,
                      "max_level": serving_kw["degrade_ladder"],
                      "require_rise": max(arms) >= 2.0})):
                try:
                    check(**kwargs)
                except invariants.InvariantViolation as e:
                    violations.append(str(e))

        record["arms"] = [{k: v for k, v in s.items()
                          if k not in ("ttft_ms", "itl_ms")}
                          for s in arm_stats]
        record["degrade_peak"] = max(poller.levels or [0])
        record["degrade_final"] = (poller.levels or [0])[-1]
        snap = engine.metrics.snapshot()
        record["counters"] = {
            k: snap[k]
            for k in ("degrade_transitions", "slo_ttft_violations",
                      "slo_itl_violations", "goodput_tokens",
                      "requests_shed")}
        record["bounds_ms"] = {"ttft_1x": round(ttft_bound_ms, 1),
                               "itl_all": round(itl_bound_ms, 1)}
        record["value"] = sum(s["completed"] for s in arm_stats)
        record["violations"] = violations
        if inject_slo_regression:
            # verdict inverts: ok iff the injected stall WAS caught
            record["injected_caught"] = slo_violated
            record["ok"] = slo_violated and not violations
        else:
            record["ok"] = not violations
        record["completed"] = record["ok"]
    finally:
        engine.close()
    record["wall_s"] = round(time.monotonic() - t0, 1)
    if not record["ok"]:
        print(f"chaos_storm: VIOLATION — repro: {record['repro']}",
              file=sys.stderr)
        for v in record["violations"]:
            print(f"  {v}", file=sys.stderr)
    return record


class _null_ctx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def run_smoke(n_requests: int, new_tokens: int) -> dict:
    runs = [run_one(seed, require, n_requests=n_requests,
                    new_tokens=new_tokens)
            for seed, require in SMOKE_SEEDS]
    # the vacuity pin rides along: one injected regression MUST trip
    inj = run_one(SMOKE_SEEDS[0][0], SMOKE_SEEDS[0][1],
                  n_requests=n_requests, new_tokens=new_tokens,
                  inject_slo_regression=True)
    runs.append(inj)
    ok = all(r["ok"] for r in runs)
    return {
        "metric": "storm_seeds_green",
        "value": sum(1 for r in runs if r["ok"]),
        "unit": (f"seeded storms with every perf law green (of "
                 f"{len(runs)}: plain/speculative/adapters + one "
                 "injected-regression catch)"),
        "completed": ok,
        "ok": ok,
        "seed": SMOKE_SEEDS[0][0],
        "seeds": [list(s) for s in SMOKE_SEEDS],
        "runs": runs,
        "wall_s": round(sum(r["wall_s"] for r in runs), 1),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=None,
                    help="run ONE seeded storm (config biases + "
                         "workload trace + arrival schedule all derive "
                         "from it)")
    ap.add_argument("--require", type=str, default="",
                    help="comma-separated config biases (part of the "
                         "repro line): spec, adapters")
    ap.add_argument("--smoke", action="store_true",
                    help="fixed seed set for bench extras / CI: plain, "
                         "speculative, and adapter-skew storms plus "
                         "one injected-SLO-regression catch")
    ap.add_argument("--requests", type=int, default=10,
                    help="trace length per arm")
    ap.add_argument("--new_tokens", type=int, default=8,
                    help="max decode length per request")
    ap.add_argument("--arms", type=str, default="0.5,1.0,2.0",
                    help="offered-load multiples of the calibrated "
                         "sustainable rate, comma-separated")
    ap.add_argument("--inject_slo_regression", action="store_true",
                    help="stall the engine loop mid-storm and REQUIRE "
                         "the SLO law to catch it (exit 0 iff caught) "
                         "— the perf-law checker-not-vacuous pin")
    ap.add_argument("--out", type=str, default=None,
                    help="also write the JSON record here")
    args = ap.parse_args(argv)

    cc.force_host_devices(N_DEVICES)
    ensure_env_platform()
    require = tuple(t for t in args.require.split(",") if t)
    arms = tuple(float(a) for a in args.arms.split(","))

    if args.smoke:
        record = run_smoke(args.requests, args.new_tokens)
    else:
        seed = args.seed if args.seed is not None else 17
        record = run_one(seed, require, n_requests=args.requests,
                         new_tokens=args.new_tokens, arms=arms,
                         inject_slo_regression=args.inject_slo_regression)
    cc.emit_record(record, args.out, seed=record.get("seed", 0))
    return 0 if record["completed"] else 1


if __name__ == "__main__":
    sys.exit(main())
