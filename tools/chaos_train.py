"""Scripted chaos run: train a tiny model while faults fire, measure
recovery.

The unit suite (tests/test_resilience.py) proves each resilience path
in isolation; this tool composes them into ONE run the way a bad day
on a preemptible cluster would — transient checkpoint-write failures,
a NaN streak mid-run, a corrupted checkpoint on disk — and reports
whether training still completed, how many rollbacks it took, and the
recovery latency (wall-clock cost of a rollback: detect → restore →
resume). Emits ONE BENCH-style JSON record on stdout (and to --out),
like bench.py, so recovery-latency regressions surface in the
`BENCH_*.json` extras.

Modes:
- `--smoke` (bench extras / CI): tiny model, short schedule, fixed
  fault script — finishes in well under a minute on CPU;
- default: the same scenario at a configurable size
  (`--train_iters`, `--hidden_size`), plus `--faults SPEC` to override
  the fault schedule with a `MEGATRON_TPU_FAULTS`-syntax spec (e.g.
  "write_error@2,nan@5,nan@6,delay@8:2.0").

  JAX_PLATFORMS=cpu python tools/chaos_train.py --smoke [--out FILE]
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform


class _SyntheticDataset:
    """Map-style stand-in for GPTDataset: index -> deterministic tokens.
    Gives the chaos run a REAL BatchIterator (random sampler + the
    exact-resume state protocol) instead of an opaque generator, so the
    rollback path exercises bit-exact replay + quarantine end-to-end."""

    def __init__(self, n: int, seq_length: int, vocab: int):
        self._n, self._seq, self._vocab = n, seq_length, vocab

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        import numpy as np
        rng = np.random.RandomState((int(i) * 9973 + 7) % (2 ** 31))
        return {"text": rng.randint(0, self._vocab,
                                    size=self._seq + 1).astype(np.int64)}


def run_chaos(train_iters: int, hidden_size: int, fault_spec: str,
              workdir: str) -> dict:
    import jax
    import json as json_mod

    from megatron_tpu.config import (DataConfig, MegatronConfig,
                                     ModelConfig, OptimizerConfig,
                                     ResilienceConfig, TrainingConfig)
    from megatron_tpu.data.samplers import BatchIterator
    from megatron_tpu.resilience import (FaultInjector, integrity,
                                         use_fault_injector)
    from megatron_tpu.training import checkpointing as ckpt
    from megatron_tpu.training import init_train_state
    from megatron_tpu.training.loop import train

    model = ModelConfig(num_layers=2, hidden_size=hidden_size,
                        num_attention_heads=2, vocab_size=64,
                        seq_length=16).derived()
    cfg = MegatronConfig(
        model=model,
        optimizer=OptimizerConfig(lr=1e-3),
        training=TrainingConfig(micro_batch_size=1, global_batch_size=2,
                                train_iters=train_iters, log_interval=100,
                                save_interval=2, checkpoint_dir=workdir),
        data=DataConfig(num_workers=0),
        resilience=ResilienceConfig(max_consecutive_nonfinite=2,
                                    keep_last_k=3, io_backoff_s=0.05,
                                    io_backoff_max_s=0.2),
    ).validate(n_devices=1)

    # small enough to wrap epochs mid-run, so the quarantine replay also
    # crosses an epoch boundary in longer (non-smoke) schedules
    dataset = _SyntheticDataset(max(train_iters + 4, 12),
                                model.seq_length, model.vocab_size)

    def make_iterator(consumed, data_state=None):
        it = BatchIterator(dataset, cfg.training.micro_batch_size, 1,
                           cfg.num_microbatches,
                           consumed_samples=consumed,
                           dataloader_type="cyclic",
                           seed=cfg.training.seed)
        if data_state:
            it.load_state_dict(data_state)
        return it

    root = workdir
    timeline = {"saves": 0, "rollback_at": None, "resumed_at": None}

    def save_fn(st, iteration, consumed, data_state=None,
                quarantine=None):
        ckpt.save_checkpoint(root, st, cfg, iteration, consumed,
                             data_state=data_state, quarantine=quarantine)
        timeline["saves"] += 1

    example = init_train_state(jax.random.PRNGKey(99), cfg)

    def load_fn():
        timeline["rollback_at"] = time.monotonic()
        out = ckpt.load_checkpoint(root, example,
                                   resilience=cfg.resilience)
        timeline["resumed_at"] = time.monotonic()
        return out

    def reset_data_fn(consumed, rollbacks, data_state=None):
        # EXACT replay: same seed + checkpointed iterator state; the
        # loop quarantines the poisoned window (never re-seeds)
        return make_iterator(consumed, data_state)

    injector = FaultInjector.from_env(fault_spec)
    assert injector is not None, f"empty fault spec {fault_spec!r}"

    t0 = time.monotonic()
    with use_fault_injector(injector):
        state, consumed = train(
            cfg, make_iterator(0), mesh=None,
            rng=jax.random.PRNGKey(cfg.training.seed),
            save_fn=save_fn, load_fn=load_fn,
            reset_data_fn=reset_data_fn)
    wall_s = time.monotonic() - t0

    # quarantine audit: the final checkpoint's metadata must carry the
    # poison windows the rollback skipped (exact order, no NaN spiral)
    tag = ckpt.read_tracker(root)
    with open(os.path.join(root, f"iter_{int(tag):07d}",
                           "metadata.json")) as f:
        final_meta = json_mod.load(f)
    quarantine = final_meta.get("quarantine", [])
    data_state_saved = final_meta.get("data_state") is not None

    # post-run corruption drill #1: bit-rot the tracker-named checkpoint
    # and prove the fallback restores the previous valid one
    FaultInjector.corrupt_checkpoint(
        os.path.join(root, f"iter_{int(tag):07d}"))
    t1 = time.monotonic()
    recovered, rec_it, _ = ckpt.load_checkpoint(
        root, example, resilience=cfg.resilience)
    fallback_s = time.monotonic() - t1

    # post-run corruption drill #2: corrupt an on-disk dataset every
    # way FaultInjector knows and prove each is caught at open with a
    # typed error (never a downstream numpy error / NaN spiral), even
    # with a previously-cached clean handle for the same prefix
    data_faults_detected = _data_corruption_drill(workdir)

    recovery_s = (timeline["resumed_at"] - timeline["rollback_at"]
                  if timeline["rollback_at"] is not None else None)
    fired = {}
    for kind, _ in injector.fired:
        fired[kind] = fired.get(kind, 0) + 1
    valid = [it for it, d in integrity.list_iter_checkpoints(root)
             if integrity.verify_checkpoint(d)[0]]
    expect_quarantine = timeline["rollback_at"] is not None
    ok = (int(state.iteration) == train_iters and recovered is not None
          and rec_it < int(tag) and data_state_saved
          and all(data_faults_detected.values())
          and (bool(quarantine) or not expect_quarantine))
    return {
        "metric": "chaos_recovery_latency_s",
        "value": round(recovery_s, 3) if recovery_s is not None else None,
        "unit": (f"s detect->restore->resume ({train_iters} iters, "
                 f"faults {fault_spec})"),
        "vs_baseline": None,
        "completed": ok,
        "final_iteration": int(state.iteration),
        "consumed_samples": int(consumed),
        "faults_fired": fired,
        "saves": timeline["saves"],
        "quarantine_windows": quarantine,
        "exact_resume_state_saved": data_state_saved,
        "corrupt_fallback_iteration": int(rec_it),
        "corrupt_fallback_s": round(fallback_s, 3),
        "data_faults_detected": data_faults_detected,
        "valid_checkpoints": valid,
        "wall_s": round(wall_s, 1),
    }


def _data_corruption_drill(workdir: str) -> dict:
    from megatron_tpu.resilience.faults import FaultInjector
    return FaultInjector.dataset_corruption_drill(workdir)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny fixed scenario for bench extras / CI")
    ap.add_argument("--train_iters", type=int, default=12)
    ap.add_argument("--hidden_size", type=int, default=64)
    ap.add_argument("--faults", type=str,
                    default="write_error@2,nan@5,nan@6",
                    help="MEGATRON_TPU_FAULTS-syntax fault schedule")
    ap.add_argument("--workdir", type=str, default=None,
                    help="checkpoint dir (default: fresh tempdir)")
    ap.add_argument("--out", type=str, default=None,
                    help="also write the JSON record here")
    args = ap.parse_args(argv)

    ensure_env_platform()
    if args.smoke:
        args.train_iters, args.hidden_size = 8, 32
        args.faults = "write_error@2,nan@3,nan@4"

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_train_")
    cleanup = args.workdir is None
    try:
        record = run_chaos(args.train_iters, args.hidden_size,
                           args.faults, workdir)
    finally:
        if cleanup:
            shutil.rmtree(workdir, ignore_errors=True)
    line = json.dumps(record)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if record["completed"] else 1


if __name__ == "__main__":
    sys.exit(main())
