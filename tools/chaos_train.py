"""Scripted chaos run: train a tiny model while faults fire, measure
recovery.

The unit suite (tests/test_resilience.py) proves each resilience path
in isolation; this tool composes them into ONE run the way a bad day
on a preemptible cluster would — transient checkpoint-write failures,
a NaN streak mid-run, a corrupted checkpoint on disk — and reports
whether training still completed, how many rollbacks it took, and the
recovery latency (wall-clock cost of a rollback: detect → restore →
resume). Emits ONE BENCH-style JSON record on stdout (and to --out),
like bench.py, so recovery-latency regressions surface in the
`BENCH_*.json` extras.

Modes:
- `--smoke` (bench extras / CI): tiny model, short schedule, fixed
  fault script — finishes in well under a minute on CPU;
- default: the same scenario at a configurable size
  (`--train_iters`, `--hidden_size`), plus `--faults SPEC` to override
  the fault schedule with a `MEGATRON_TPU_FAULTS`-syntax spec (e.g.
  "write_error@2,nan@5,nan@6,delay@8:2.0").

  JAX_PLATFORMS=cpu python tools/chaos_train.py --smoke [--out FILE]
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform


def run_chaos(train_iters: int, hidden_size: int, fault_spec: str,
              workdir: str) -> dict:
    import jax
    import numpy as np

    from megatron_tpu.config import (DataConfig, MegatronConfig,
                                     ModelConfig, OptimizerConfig,
                                     ResilienceConfig, TrainingConfig)
    from megatron_tpu.resilience import (FaultInjector, integrity,
                                         use_fault_injector)
    from megatron_tpu.training import checkpointing as ckpt
    from megatron_tpu.training import init_train_state
    from megatron_tpu.training.loop import train

    model = ModelConfig(num_layers=2, hidden_size=hidden_size,
                        num_attention_heads=2, vocab_size=64,
                        seq_length=16).derived()
    cfg = MegatronConfig(
        model=model,
        optimizer=OptimizerConfig(lr=1e-3),
        training=TrainingConfig(micro_batch_size=1, global_batch_size=2,
                                train_iters=train_iters, log_interval=100,
                                save_interval=2, checkpoint_dir=workdir),
        data=DataConfig(num_workers=0),
        resilience=ResilienceConfig(max_consecutive_nonfinite=2,
                                    keep_last_k=3, io_backoff_s=0.05,
                                    io_backoff_max_s=0.2),
    ).validate(n_devices=1)

    def batches(seed=0):
        i = 0
        while True:
            tokens = jax.random.randint(jax.random.PRNGKey(seed * 1000 + i),
                                        (2, 1, 17), 0, 64)
            yield {"tokens": np.asarray(tokens),
                   "loss_mask": np.ones((2, 1, 16), np.float32)}
            i += 1

    root = workdir
    timeline = {"saves": 0, "rollback_at": None, "resumed_at": None}

    def save_fn(st, iteration, consumed):
        ckpt.save_checkpoint(root, st, cfg, iteration, consumed)
        timeline["saves"] += 1

    example = init_train_state(jax.random.PRNGKey(99), cfg)

    def load_fn():
        timeline["rollback_at"] = time.monotonic()
        out = ckpt.load_checkpoint(root, example,
                                   resilience=cfg.resilience)
        timeline["resumed_at"] = time.monotonic()
        return out

    injector = FaultInjector.from_env(fault_spec)
    assert injector is not None, f"empty fault spec {fault_spec!r}"

    t0 = time.monotonic()
    with use_fault_injector(injector):
        state, consumed = train(
            cfg, batches(0), mesh=None,
            rng=jax.random.PRNGKey(cfg.training.seed),
            save_fn=save_fn, load_fn=load_fn,
            reset_data_fn=lambda c, r: batches(r))
    wall_s = time.monotonic() - t0

    # post-run corruption drill: bit-rot the tracker-named checkpoint
    # and prove the fallback restores the previous valid one
    tag = ckpt.read_tracker(root)
    FaultInjector.corrupt_checkpoint(
        os.path.join(root, f"iter_{int(tag):07d}"))
    t1 = time.monotonic()
    recovered, rec_it, _ = ckpt.load_checkpoint(
        root, example, resilience=cfg.resilience)
    fallback_s = time.monotonic() - t1

    recovery_s = (timeline["resumed_at"] - timeline["rollback_at"]
                  if timeline["rollback_at"] is not None else None)
    fired = {}
    for kind, _ in injector.fired:
        fired[kind] = fired.get(kind, 0) + 1
    valid = [it for it, d in integrity.list_iter_checkpoints(root)
             if integrity.verify_checkpoint(d)[0]]
    ok = (int(state.iteration) == train_iters and recovered is not None
          and rec_it < int(tag))
    return {
        "metric": "chaos_recovery_latency_s",
        "value": round(recovery_s, 3) if recovery_s is not None else None,
        "unit": (f"s detect->restore->resume ({train_iters} iters, "
                 f"faults {fault_spec})"),
        "vs_baseline": None,
        "completed": ok,
        "final_iteration": int(state.iteration),
        "consumed_samples": int(consumed),
        "faults_fired": fired,
        "saves": timeline["saves"],
        "corrupt_fallback_iteration": int(rec_it),
        "corrupt_fallback_s": round(fallback_s, 3),
        "valid_checkpoints": valid,
        "wall_s": round(wall_s, 1),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny fixed scenario for bench extras / CI")
    ap.add_argument("--train_iters", type=int, default=12)
    ap.add_argument("--hidden_size", type=int, default=64)
    ap.add_argument("--faults", type=str,
                    default="write_error@2,nan@5,nan@6",
                    help="MEGATRON_TPU_FAULTS-syntax fault schedule")
    ap.add_argument("--workdir", type=str, default=None,
                    help="checkpoint dir (default: fresh tempdir)")
    ap.add_argument("--out", type=str, default=None,
                    help="also write the JSON record here")
    args = ap.parse_args(argv)

    ensure_env_platform()
    if args.smoke:
        args.train_iters, args.hidden_size = 8, 32
        args.faults = "write_error@2,nan@3,nan@4"

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_train_")
    cleanup = args.workdir is None
    try:
        record = run_chaos(args.train_iters, args.hidden_size,
                           args.faults, workdir)
    finally:
        if cleanup:
            shutil.rmtree(workdir, ignore_errors=True)
    line = json.dumps(record)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if record["completed"] else 1


if __name__ == "__main__":
    sys.exit(main())
