"""Scripted live-weight chaos drill: rolling fleet upgrades under
sustained traffic with failures injected at the worst moments, measure
that nothing 503s, nothing strands, and nothing moves a token.

tools/chaos_router.py proves the ROUTER survives a replica's bad hour;
this tool proves the fleet survives its WEIGHT UPGRADES (docs/serving.md
"Live weights & rolling upgrade"). Three drills, each over a real
`EngineRouter` with real `ServingEngine` replicas and real
manifest-sealed checkpoints on disk:

1. **rolling upgrade under load + kill the DRAINING replica mid-swap**:
   traffic flows while `rolling_upgrade` walks the fleet; the moment
   replica 0 enters its planned drain, it is killed (`close()` — the
   in-process analogue of the pod dying mid-upgrade). Contract: the
   rollout ABORTS typed (`RollingUpgradeError`), the fleet is
   DEGRADED-not-down and keeps serving, zero futures strand, and every
   COMPLETED request is token-exact vs a serial oracle at its admitted
   version (N or N+1 — a mid-rollout fleet legitimately serves both).
2. **corrupt-checkpoint publish mid-watch**: a `CheckpointWatcher`
   drives the fleet; a GOOD publish upgrades it hands-free, then a
   CORRUPT publish lands. Contract: the manifest gate refuses it before
   any device transfer, the fleet stays on the good version,
   `weight_swap_failures` counts it, and the watcher does NOT retry the
   same tag (no restart loop) — but the NEXT good publish applies.
3. **upgrade racing the disaggregated handoff**: a rolling upgrade over
   DISAGGREGATED replicas (each a prefill-group/decode-group pair)
   under live traffic. Contract: zero 503s, every completion
   token-exact at its admitted version — which pins that the swap lands
   on BOTH chip groups atomically per replica (a prefill-N / decode-N+1
   split would corrupt tokens, not just flip versions) — and the
   survivors keep handing off throughout.

Every drill finishes with a system-wide `invariants.check_all` sweep
(serving/invariants.py) — the conservation / typed-terminal / KV /
schema / healthz laws hold through every refused swap and aborted
rollout, on top of the drills' own version-exactness assertions.

Emits ONE BENCH-style JSON record on stdout (and to --out), like
chaos_router.py, so live-weight regressions surface in the
`BENCH_*.json` extras. The scaffolding (tiny model/fleet builders,
checkpoint publish helpers, serial oracle) lives in
tools/chaos_common.py, shared with chaos_serve.py / chaos_router.py /
chaos_mesh.py.

  JAX_PLATFORMS=cpu python tools/chaos_upgrade.py --smoke [--out FILE]
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform
from tools.chaos_common import (corrupt_payload as _corrupt_payload,
                                emit_record, force_host_devices,
                                invariant_sweep,
                                publish_checkpoint as _publish,
                                serial_oracle as _serial_oracle,
                                tiny_generator, tiny_model_cfg)


def _model_cfg():
    return tiny_model_cfg(compute="float32")


def _versioned_fleet(serving_kwargs, n_replicas=2, devices_per=None):
    """(router, engines, gen_v1, gen_v2, ckpt_root, ckpt_v2): a fleet
    serving version 1 with version 2 already published to disk."""
    import jax

    from megatron_tpu.config import ServingConfig
    from megatron_tpu.serving import EngineRouter, ServingEngine

    model = _model_cfg()
    # eos_id=-1: no early EOS, deterministic request lifetimes
    gen1 = tiny_generator(model, seed=0)
    gen2 = tiny_generator(model, seed=1)
    root = tempfile.mkdtemp(prefix="chaos_upgrade_")
    d2 = _publish(root, model, gen2.params, 2)
    serving = ServingConfig(**serving_kwargs).validate(model)
    if devices_per:
        devs = jax.devices()
        engines = [ServingEngine(gen1, serving,
                                 devices=devs[i * devices_per:
                                              (i + 1) * devices_per])
                   for i in range(n_replicas)]
    else:
        engines = [ServingEngine(gen1, serving)
                   for _ in range(n_replicas)]
    router = EngineRouter(engines, max_retries=2,
                          heartbeat_timeout_s=3.0, probe_backoff_s=0.2)
    return router, engines, gen1, gen2, root, d2


def _load_workers(router, new_tokens, n_workers=3):
    """Background greedy traffic: (results, stop, threads). Each result
    is (prompt, seed, tokens|None, error|None)."""
    from megatron_tpu.serving import SamplingOptions
    sampling = SamplingOptions(temperature=0.0)
    results, stop = [], threading.Event()
    lock = threading.Lock()

    def worker(wid):
        i = 0
        while not stop.is_set():
            p = [3 + (wid + i) % 5, 7, 11]
            seed = 1000 * wid + i
            try:
                r = router.submit(p, new_tokens, sampling, seed=seed)
                toks, _ = r.result(timeout=120)
                with lock:
                    results.append((p, seed, toks, None))
            except Exception as e:  # noqa: BLE001 — counted by caller
                with lock:
                    results.append((p, seed, None, e))
            i += 1

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(n_workers)]
    for t in threads:
        t.start()
    return results, stop, threads


def _classify(results, want1, want2, new_tokens):
    """(errors, at_v1, at_v2, mismatches) over completed results."""
    errors, v1, v2, bad = [], 0, 0, []
    for p, seed, toks, err in results:
        if err is not None:
            errors.append(repr(err))
        elif toks == want1(p, new_tokens, seed):
            v1 += 1
        elif toks == want2(p, new_tokens, seed):
            v2 += 1
        else:
            bad.append((p, seed, toks))
    return errors, v1, v2, bad


def kill_draining_drill(new_tokens: int) -> dict:
    """Rolling upgrade under load; kill the DRAINING replica mid-swap.
    The rollout must abort typed with the fleet degraded-not-down and
    every completion token-exact at its admitted version."""
    from megatron_tpu.serving import RollingUpgradeError, SamplingOptions

    router, engines, gen1, gen2, root, d2 = _versioned_fleet(
        dict(num_slots=2, max_queue=64, max_len=128))
    want1, want2 = _serial_oracle(gen1), _serial_oracle(gen2)
    sampling = SamplingOptions(temperature=0.0)
    try:
        for eng in engines:
            eng.generate([3, 1, 4], 2, sampling, seed=0)
        # widen the mid-swap window deterministically: replica 0's
        # apply stalls briefly (the _fetch-seam monkeypatch idiom of
        # chaos_router), so the kill below reliably lands while the
        # replica is DRAINING or mid-apply — never after a completed
        # upgrade. A long direct request adds real drain work too.
        orig_apply = engines[0]._apply_swap

        def slow_apply(ticket):
            time.sleep(0.5)
            return orig_apply(ticket)

        engines[0]._apply_swap = slow_apply
        engines[0].submit([2, 2, 2], 80, sampling, seed=0)
        results, stop, threads = _load_workers(router, new_tokens)
        time.sleep(0.2)

        aborted = []

        def upgrade():
            try:
                router.rolling_upgrade(d2, swap_timeout_s=120)
            except RollingUpgradeError as e:
                aborted.append(repr(e))

        up = threading.Thread(target=upgrade)
        up.start()
        # the kill: the moment replica 0 enters its planned drain
        t0 = time.monotonic()
        while not router.replicas[0].upgrading \
                and time.monotonic() - t0 < 30:
            time.sleep(0.002)
        time.sleep(0.05)
        engines[0].close()  # the draining replica dies mid-swap
        up.join(timeout=180)
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join()
        errors, v1, v2, bad = _classify(results, want1, want2,
                                        new_tokens)
        health = router.health()
        snap = router.aggregate_snapshot()
        # the degraded fleet still serves (on version 1 — the rollout
        # died before any replica upgraded)
        post = router.submit([9, 9, 8], 4, sampling, seed=99)
        post_toks, _ = post.result(timeout=60)
        post_exact = post_toks == want1([9, 9, 8], 4, 99)
        inv = invariant_sweep(router, [post])
    finally:
        router.close()
    return {
        "submitted": len(results), "errors": len(errors),
        "completed_v1": v1, "completed_v2": v2,
        "version_mismatches": len(bad),
        "rollout_aborted_typed": len(aborted) == 1,
        "health_state": health["state"],
        "healthz_ready": bool(health["healthy"]),
        "weight_swap_failures": int(snap["weight_swap_failures"]),
        "post_kill_serve_exact": post_exact,
        "invariants_ok": inv["ok"],
        "invariant_violations": inv["violations"],
        "ok": (not errors and not bad and len(aborted) == 1
               and health["state"] == "degraded" and health["healthy"]
               and post_exact and (v1 + v2) == len(results)
               and (v1 + v2) >= 4 and inv["ok"]),
    }


def corrupt_watch_drill(new_tokens: int) -> dict:
    """CheckpointWatcher drives the fleet: a good publish upgrades it
    hands-free; a corrupt publish is refused at the manifest gate with
    the fleet staying put and NO retry loop; the next good publish
    applies."""
    import jax

    from megatron_tpu.inference.generation import Generator
    from megatron_tpu.models import language_model as lm
    from megatron_tpu.serving import CheckpointWatcher, SamplingOptions

    router, engines, gen1, gen2, root, d2 = _versioned_fleet(
        dict(num_slots=2, max_queue=64, max_len=128))
    want2 = _serial_oracle(gen2)
    sampling = SamplingOptions(temperature=0.0)
    model = _model_cfg()
    try:
        for eng in engines:
            eng.generate([3, 1, 4], 2, sampling, seed=0)
        watcher = CheckpointWatcher(router, root, interval_s=0.1)
        # beat 1: the good v2 publish (already on disk) applies
        applied = watcher.poll_once()
        snap1 = router.aggregate_snapshot()
        v2_serving = (snap1["weight_version_min"] == 2.0
                      == snap1["weight_version_max"])
        r = router.submit([5, 6, 7], new_tokens, sampling, seed=5)
        toks, _ = r.result(timeout=60)
        exact_v2 = toks == want2([5, 6, 7], new_tokens, 5)
        # beat 2: a CORRUPT v3 publish — refused, counted, no loop
        p3 = lm.model_init(jax.random.PRNGKey(2), model)
        d3 = _publish(root, model, p3, 3)
        _corrupt_payload(d3)
        refused = not watcher.poll_once()
        failures_1 = watcher.failures
        re_polled = not watcher.poll_once()  # same tag: skipped
        failures_2 = watcher.failures
        snap2 = router.aggregate_snapshot()
        stayed = (snap2["weight_version_min"] == 2.0
                  == snap2["weight_version_max"])
        # beat 3: the NEXT good publish applies
        p4 = lm.model_init(jax.random.PRNGKey(3), model)
        _publish(root, model, p4, 4)
        recovered = watcher.poll_once()
        snap3 = router.aggregate_snapshot()
        v4_serving = (snap3["weight_version_min"] == 4.0
                      == snap3["weight_version_max"])
        health = router.health()
        inv = invariant_sweep(router)
    finally:
        router.close()
    return {
        "good_publish_applied": bool(applied),
        "fleet_on_v2": v2_serving, "serve_exact_v2": exact_v2,
        "corrupt_publish_refused": refused,
        "no_retry_loop": re_polled and failures_1 == failures_2 == 1,
        "fleet_stayed_on_v2": stayed,
        "weight_swap_failures": int(snap2["weight_swap_failures"]),
        "next_publish_applied": bool(recovered),
        "fleet_on_v4": v4_serving,
        "health_state": health["state"],
        "invariants_ok": inv["ok"],
        "invariant_violations": inv["violations"],
        "ok": (applied and v2_serving and exact_v2 and refused
               and re_polled and failures_2 == 1 and stayed
               and int(snap2["weight_swap_failures"]) >= 1
               and recovered and v4_serving
               and health["state"] == "running" and inv["ok"]),
    }


def disagg_race_drill(new_tokens: int) -> dict:
    """Rolling upgrade racing the prefill->decode handoff on a
    DISAGGREGATED fleet: zero 503s, every completion token-exact at its
    admitted version (pins the per-replica both-groups-atomic swap),
    handoffs keep advancing."""
    import jax

    if len(jax.devices()) < 4:
        return {"skipped": f"{len(jax.devices())} device(s) < 4 "
                           "(2 disaggregated replicas)", "ok": True}
    router, engines, gen1, gen2, root, d2 = _versioned_fleet(
        dict(num_slots=2, max_queue=64, max_len=128, kv_block_size=16,
             disaggregate_prefill=True),
        devices_per=2)
    want1, want2 = _serial_oracle(gen1), _serial_oracle(gen2)
    from megatron_tpu.serving import SamplingOptions
    sampling = SamplingOptions(temperature=0.0)
    try:
        for eng in engines:
            eng.generate([3, 1, 4], 2, sampling, seed=0)
        results, stop, threads = _load_workers(router, new_tokens)
        time.sleep(0.3)
        version = router.rolling_upgrade(d2, swap_timeout_s=120)
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join()
        errors, v1, v2, bad = _classify(results, want1, want2,
                                        new_tokens)
        snap = router.aggregate_snapshot()
        health = router.health()
        # the upgraded fleet still hands off end to end at v2
        pre_handoffs = int(snap["handoffs"])
        post = router.submit([9, 9, 8], 4, sampling, seed=99)
        post_toks, _ = post.result(timeout=60)
        post_exact = post_toks == want2([9, 9, 8], 4, 99)
        snap_post = router.aggregate_snapshot()
        inv = invariant_sweep(router, [post])
    finally:
        router.close()
    return {
        "submitted": len(results), "errors": len(errors),
        "completed_v1": v1, "completed_v2": v2,
        "version_mismatches": len(bad),
        "upgraded_to": version.label,
        "rolling_upgrades": int(snap["rolling_upgrades"]),
        "health_state": health["state"],
        "handoffs": int(snap_post["handoffs"]),
        "post_upgrade_serve_exact": post_exact,
        "invariants_ok": inv["ok"],
        "invariant_violations": inv["violations"],
        "ok": (not errors and not bad and (v1 + v2) == len(results)
               and (v1 + v2) >= 4 and v2 >= 1
               and int(snap["rolling_upgrades"]) == 1
               and health["state"] == "running" and post_exact
               and int(snap_post["handoffs"]) > pre_handoffs
               and inv["ok"]),
    }


def run_chaos(new_tokens: int) -> dict:
    t0 = time.monotonic()
    kill = kill_draining_drill(new_tokens)
    watch = corrupt_watch_drill(new_tokens)
    disagg = disagg_race_drill(new_tokens)
    wall_s = time.monotonic() - t0
    ok = kill["ok"] and watch["ok"] and disagg["ok"]
    return {
        "metric": "upgrade_chaos_swap_failures_contained",
        "value": (kill.get("weight_swap_failures", 0)
                  + watch.get("weight_swap_failures", 0)),
        "unit": ("refused/failed swaps across the kill + corrupt-watch "
                 "drills (fleet kept serving through every one)"),
        "vs_baseline": None,
        "completed": ok,
        "kill_draining": kill,
        "corrupt_watch": watch,
        "disagg_race": disagg,
        "wall_s": round(wall_s, 1),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny fixed scenario for bench extras / CI")
    ap.add_argument("--new_tokens", type=int, default=12,
                    help="decode length of the drill requests")
    ap.add_argument("--out", type=str, default=None,
                    help="also write the JSON record here")
    args = ap.parse_args(argv)

    # the disaggregated race drill needs 4 devices (2 replicas x 2 chip
    # groups)
    force_host_devices(4)
    ensure_env_platform()
    if args.smoke:
        args.new_tokens = 8

    record = run_chaos(args.new_tokens)
    emit_record(record, args.out, seed=0)  # scripted: fixed workload
    return 0 if record["completed"] else 1


if __name__ == "__main__":
    sys.exit(main())
