"""Offline checkpoint utility: validate or re-save under a target topology.

Counterpart of the reference's resharding toolchain
(ref: tools/checkpoint_util.py + checkpoint_loader_megatron.py +
checkpoint_saver_megatron.py, ~900 lines that rewrite per-rank
mp_rank_{tp}_{pp} shards). Here checkpoints are TOPOLOGY-FREE — one
logical tree, re-laid-out at load against the current mesh
(training/checkpointing.py "Differences by design") — so *resharding*
is a load-time no-op and this tool's jobs are the ones that remain
meaningful offline:

- validate (default): restore the checkpoint under the target
  tp/pp/dp on a VIRTUAL CPU mesh and report per-leaf placement +
  per-device bytes — a pre-flight proof the layout works before
  burning pod time. The reference cannot do this below real GPUs.
- --save_dir: write a re-saved logical copy (e.g. --release to roll a
  weights-only release checkpoint for conversion/serving).

  python tools/checkpoint_util.py --load_dir ckpts/llama7b \\
      --target_tensor_parallel_size 4 --target_pipeline_parallel_size 2 \\
      --target_data_parallel_size 1
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser("checkpoint_util", description=__doc__)
    p.add_argument("--load_dir", required=True)
    p.add_argument("--save_dir", default=None)
    p.add_argument("--target_tensor_parallel_size", type=int, default=1)
    p.add_argument("--target_pipeline_parallel_size", type=int, default=1)
    p.add_argument("--target_data_parallel_size", type=int, default=1)
    p.add_argument("--release", action="store_true",
                   help="save weights-only (release) checkpoint")
    args = p.parse_args(argv)
    if args.release and not args.save_dir:
        p.error("--release requires --save_dir (nothing would be written)")

    tp, pp, dp = (args.target_tensor_parallel_size,
                  args.target_pipeline_parallel_size,
                  args.target_data_parallel_size)
    n = tp * pp * dp
    # virtual CPU devices for the target layout — must be set before jax
    # backends initialize (the tool is offline by design: cpu). An
    # inherited device-count flag is REPLACED, not kept: the target
    # layout dictates the count here.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import re
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", "")).strip()
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n}").strip()
    import dataclasses

    import jax
    jax.config.update("jax_platforms", "cpu")

    from megatron_tpu.parallel.mesh import build_mesh
    from megatron_tpu.training import checkpointing as ckpt
    from megatron_tpu.training.train_step import init_train_state

    cfg = ckpt.load_config_from_checkpoint(args.load_dir)
    assert cfg is not None, (
        f"{args.load_dir}: no checkpoint (or no embedded config) found")
    cfg = dataclasses.replace(
        cfg, parallel=dataclasses.replace(
            cfg.parallel, tensor_parallel=tp, pipeline_parallel=pp,
            data_parallel=dp)).validate(n_devices=n)
    mesh = build_mesh(cfg.parallel)
    print(f"target mesh: dp={dp} pp={pp} tp={tp} "
          f"({n} virtual cpu devices)")

    # abstract state template (no concrete init) + the exact shardings the
    # sharded train step would use (train_step.py make_train_step)
    from megatron_tpu.training.train_step import state_shardings

    rng = jax.random.PRNGKey(0)
    example = jax.eval_shape(lambda r: init_train_state(r, cfg), rng)
    # ONE source of truth: the same sharding tree the sharded train step
    # would jit with, so this validation proves the real layout
    shardings = state_shardings(cfg, mesh, example.params,
                                has_opt=example.opt_state is not None)

    state, iteration, consumed = ckpt.load_checkpoint(
        args.load_dir, example, shardings=shardings)
    assert state is not None, f"restore failed from {args.load_dir}"
    # a release / no-optim checkpoint leaves example's ABSTRACT opt_state
    # in place of a restored one; drop it so a re-save cannot try to
    # serialize ShapeDtypeStructs
    if state.opt_state is not None and any(
            not hasattr(l, "addressable_shards")
            for l in jax.tree.leaves(state.opt_state)):
        state = state._replace(opt_state=None)
        print("note: checkpoint carries no optimizer state "
              "(release/no-optim save); validating weights only")
    total = sum(l.size * l.dtype.itemsize
                for l in jax.tree.leaves(state.params))
    per_dev = {}
    for l in jax.tree.leaves(state):
        for sh in getattr(l, "addressable_shards", []):
            per_dev[sh.device.id] = (per_dev.get(sh.device.id, 0)
                                     + sh.data.size * sh.data.dtype.itemsize)
    worst = max(per_dev.values()) if per_dev else 0
    print(f"restored iter={iteration} consumed={consumed}: "
          f"params {total / 1e6:.1f} MB logical, "
          f"max per-device state {worst / 1e6:.1f} MB")

    if args.save_dir:
        ckpt.save_checkpoint(args.save_dir, state, cfg,
                             iteration=0 if args.release else iteration,
                             consumed_samples=consumed,
                             release=args.release)
        print(f"saved {'release ' if args.release else ''}checkpoint "
              f"to {args.save_dir}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
