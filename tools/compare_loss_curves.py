"""Compare two training-log loss curves sample-for-sample.

Supports the BASELINE "loss-curve-matched to the A100 baseline"
acceptance: identical data order (bit-identical index mappings) makes
curves comparable at equal consumed-sample counts. Parses the dashboard
lines both this framework and the reference emit
("iteration N | consumed samples S | ... | lm loss: X | ...").

  python tools/compare_loss_curves.py ours.log theirs.log \
      [--rtol 0.05] [--max_points 0]

Exit code 0 when every aligned point agrees within rtol, 1 otherwise.
"""
from __future__ import annotations

import argparse
import re
import sys

_LINE = re.compile(
    r"iteration\s+(\d+)(?:\s*/\s*\d+)?\s*\|\s*consumed samples[:]?"
    r"\s*(\d+).*?lm loss[:]?\s*([0-9.eE+-]+|nan|-?inf)", re.IGNORECASE)


def parse_log(path: str) -> dict[int, float]:
    """-> {consumed_samples: lm_loss} (later lines win on duplicates)."""
    out: dict[int, float] = {}
    with open(path, errors="replace") as f:
        for line in f:
            m = _LINE.search(line)
            if m:
                out[int(m.group(2))] = float(m.group(3))
    return out


def compare(a: dict[int, float], b: dict[int, float], rtol: float,
            max_points: int = 0):
    """-> (aligned, worst_rel, n_bad, report_lines)."""
    keys = sorted(set(a) & set(b))
    if max_points:
        keys = keys[:max_points]
    import math
    worst, n_bad, lines = 0.0, 0, []
    for s in keys:
        la, lb = a[s], b[s]
        if not (math.isfinite(la) and math.isfinite(lb)):
            # a nan/inf loss anywhere is divergence, never a match
            rel = float("inf")
        else:
            rel = abs(la - lb) / max(abs(lb), 1e-9)
        worst = max(worst, rel)
        flag = ""
        if rel > rtol:
            n_bad += 1
            flag = "  <-- DIVERGED"
        lines.append(f"samples {s:>12}: {la:.6f} vs {lb:.6f} "
                     f"(rel {rel:.4f}){flag}")
    return len(keys), worst, n_bad, lines


def main(argv=None) -> int:
    p = argparse.ArgumentParser("compare_loss_curves", description=__doc__)
    p.add_argument("ours")
    p.add_argument("theirs")
    p.add_argument("--rtol", type=float, default=0.05)
    p.add_argument("--max_points", type=int, default=0)
    p.add_argument("--quiet", action="store_true",
                   help="summary line only")
    args = p.parse_args(argv)

    a, b = parse_log(args.ours), parse_log(args.theirs)
    if not a or not b:
        print(f"no dashboard lines parsed ({len(a)} vs {len(b)} points)")
        return 1
    n, worst, n_bad, lines = compare(a, b, args.rtol, args.max_points)
    if not args.quiet:
        for line in lines:
            print(line)
    print(f"{n} aligned points | worst rel diff {worst:.4f} | "
          f"{n_bad} beyond rtol={args.rtol}")
    if n == 0:
        print("no common consumed-sample points — different batch sizes?")
        return 1
    return 1 if n_bad else 0


if __name__ == "__main__":
    sys.exit(main())
