"""Convert a HuggingFace Llama/Falcon checkpoint into a megatron_tpu release
checkpoint, and export back.

TPU-native port of the reference's conversion entry points
(ref: weights2megatron/weights2megatron.py:148 main,
weights2megatron/megatron2hf.py, tools/checkpoint_util.py). The reference
needs THREE tools (hf->megatron, megatron->hf, and an offline tp/pp
resharder); here there is one layout-free checkpoint, so resharding is a
load-time no-op and this tool only moves weights across formats.

  python tools/convert_hf_checkpoint.py import --hf_path X --out ckpts/llama7b \
      --family llama --size 7b
  python tools/convert_hf_checkpoint.py export --load ckpts/llama7b --hf_out Y \
      --family llama --size 7b
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform
ensure_env_platform()


def _model_cfg(family: str, size: str):
    from megatron_tpu.config import (falcon_config, llama2_config,
                                     mixtral_config)
    if family == "llama":
        return llama2_config(size)
    if family == "falcon":
        return falcon_config(size)
    if family == "mixtral":
        return mixtral_config(size)
    raise ValueError(f"unknown family {family}")


def do_import(args):
    import numpy as np

    from megatron_tpu.config import MegatronConfig
    from megatron_tpu.convert import hf_falcon_to_params, hf_llama_to_params
    from megatron_tpu.training.checkpointing import save_checkpoint
    from megatron_tpu.training.train_step import TrainState

    if args.source == "megatron":
        # reference mp_rank layout (iter_N/mp_rank_XX[_YYY]/
        # model_optim_rng.pt) — tp/pp/vpp shards merged, arch read from
        # the embedded args namespace (ref: megatron/checkpointing.py)
        from megatron_tpu.convert.megatron import (config_from_megatron_args,
                                                   load_megatron_checkpoint,
                                                   megatron_to_params)
        print(f"loading reference-megatron checkpoint from {args.hf_path}")
        sd, ref_args, meta = load_megatron_checkpoint(args.hf_path)
        print(f"  iteration={meta['iteration']} version="
              f"{meta['checkpoint_version']} tp={meta['tp']} pp={meta['pp']}")
        mcfg = config_from_megatron_args(ref_args)
        params = megatron_to_params(sd, mcfg, dtype=np.float32)
        state = TrainState(params=params, opt_state=None, iteration=0)
        cfg = MegatronConfig(model=mcfg)
        d = save_checkpoint(args.out, state, cfg, iteration=0, release=True)
        print(f"wrote release checkpoint {d}")
        return

    mcfg = _model_cfg(args.family, args.size)
    if args.source == "meta":
        # raw consolidated.NN.pth shards: merge then map, no rotary permute
        # (ref: weights2megatron/merge_llama.py:117 merge_llama dispatch)
        from megatron_tpu.convert import (merge_meta_llama,
                                          meta_llama_to_params)
        assert args.family == "llama", "meta format is llama-only"
        print(f"merging meta shards from {args.hf_path}")
        sd = merge_meta_llama(args.hf_path)
        params = meta_llama_to_params(sd, mcfg, dtype=np.float32)
    else:
        import torch
        from transformers import AutoModelForCausalLM
        print(f"loading HF model from {args.hf_path}")
        model = AutoModelForCausalLM.from_pretrained(
            args.hf_path, torch_dtype=torch.float32)
        sd = {k: v.detach().cpu().numpy()
              for k, v in model.state_dict().items()}
        del model
        from megatron_tpu.convert import hf_mixtral_to_params
        conv = {"llama": hf_llama_to_params,
                "falcon": hf_falcon_to_params,
                "mixtral": hf_mixtral_to_params}[args.family]
        params = conv(sd, mcfg, dtype=np.float32)
    state = TrainState(params=params, opt_state=None, iteration=0)
    cfg = MegatronConfig(model=mcfg)
    d = save_checkpoint(args.out, state, cfg, iteration=0, release=True)
    print(f"wrote release checkpoint {d}")


def do_export(args):
    from megatron_tpu.models import language_model as lm
    from megatron_tpu.training import checkpointing as ckpt
    from megatron_tpu.training.train_step import TrainState
    import jax

    # architecture comes from the checkpoint's embedded config.json when
    # present (finetune may have overridden vocab_size etc.); the
    # --family/--size preset is only the fallback
    saved_cfg = ckpt.load_config_from_checkpoint(args.load)
    mcfg = (saved_cfg.model if saved_cfg is not None
            else _model_cfg(args.family, args.size))
    example = TrainState(
        params=jax.eval_shape(
            lambda: lm.model_init(jax.random.PRNGKey(0), mcfg)),
        opt_state=None, iteration=0)
    state, _, _ = ckpt.load_checkpoint(args.load, example, no_load_optim=True)
    assert state is not None, f"no checkpoint under {args.load}"
    os.makedirs(args.hf_out, exist_ok=True)
    import torch
    if args.family == "llama":
        from megatron_tpu.convert import params_to_hf_llama
        from transformers import LlamaConfig
        sd = params_to_hf_llama(state.params, mcfg)
        hf_cfg = LlamaConfig(
            vocab_size=mcfg.vocab_size, hidden_size=mcfg.hidden_size,
            num_hidden_layers=mcfg.num_layers,
            num_attention_heads=mcfg.num_attention_heads,
            num_key_value_heads=mcfg.num_kv_heads,
            intermediate_size=mcfg.ffn_hidden_size,
            max_position_embeddings=mcfg.max_position_embeddings,
            rms_norm_eps=mcfg.norm_epsilon,
            tie_word_embeddings=mcfg.tie_embed_logits,
        )
    elif args.family == "mixtral":
        from megatron_tpu.convert import params_to_hf_mixtral
        from transformers import MixtralConfig
        sd = params_to_hf_mixtral(state.params, mcfg)
        hf_cfg = MixtralConfig(
            vocab_size=mcfg.vocab_size, hidden_size=mcfg.hidden_size,
            num_hidden_layers=mcfg.num_layers,
            num_attention_heads=mcfg.num_attention_heads,
            num_key_value_heads=mcfg.num_kv_heads,
            intermediate_size=mcfg.ffn_hidden_size,
            max_position_embeddings=mcfg.max_position_embeddings,
            rms_norm_eps=mcfg.norm_epsilon, rope_theta=mcfg.rope_theta,
            num_local_experts=mcfg.num_experts,
            num_experts_per_tok=mcfg.moe_top_k,
            tie_word_embeddings=mcfg.tie_embed_logits,
        )
    else:
        from megatron_tpu.convert import params_to_hf_falcon
        from transformers import FalconConfig
        sd = params_to_hf_falcon(state.params, mcfg)
        hf_cfg = FalconConfig(
            vocab_size=mcfg.vocab_size, hidden_size=mcfg.hidden_size,
            num_hidden_layers=mcfg.num_layers,
            num_attention_heads=mcfg.num_attention_heads,
            num_kv_heads=mcfg.num_kv_heads,
            ffn_hidden_size=mcfg.ffn_hidden_size,
            max_position_embeddings=mcfg.max_position_embeddings,
            rope_theta=mcfg.rope_theta,
            new_decoder_architecture=mcfg.parallel_layernorm,
            multi_query=mcfg.num_kv_heads == 1,
            parallel_attn=mcfg.parallel_attn, bias=mcfg.use_bias,
            layer_norm_epsilon=mcfg.norm_epsilon,
        )
    torch.save({k: torch.tensor(v) for k, v in sd.items()},
               os.path.join(args.hf_out, "pytorch_model.bin"))
    hf_cfg.save_pretrained(args.hf_out)
    print(f"wrote HF checkpoint to {args.hf_out}")


def main(argv=None):
    p = argparse.ArgumentParser()
    sub = p.add_subparsers(dest="cmd", required=True)
    pi = sub.add_parser("import")
    pi.add_argument("--hf_path", required=True,
                    help="HF model path, or a dir of consolidated.NN.pth "
                         "shards with --source meta")
    pi.add_argument("--out", required=True)
    pi.add_argument("--family", default="llama",
                    choices=["llama", "falcon", "mixtral"])
    pi.add_argument("--size", default="7b")
    pi.add_argument("--source", default="hf",
                    choices=["hf", "meta", "megatron"],
                    help="meta = raw Meta-llama consolidated shards; "
                         "megatron = reference iter_N/mp_rank_XX layout "
                         "(tp/pp shards merged, arch from embedded args)")
    pe = sub.add_parser("export")
    pe.add_argument("--load", required=True)
    pe.add_argument("--hf_out", required=True)
    pe.add_argument("--family", default="llama",
                    choices=["llama", "falcon", "mixtral"])
    pe.add_argument("--size", default="7b")
    args = p.parse_args(argv)
    if args.cmd == "import":
        do_import(args)
    else:
        do_export(args)


if __name__ == "__main__":
    main()
