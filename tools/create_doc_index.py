"""Build the evidence embedding index for open retrieval (REALM/ORQA).

TPU-native equivalent of the reference's indexing entry
(ref: tools/create_doc_index.py + megatron/indexer.py): run the biencoder's
context tower over a DPR-style evidence TSV and persist the
{row_id: embedding} store that tasks/main.py --task NQ searches.

  python tools/create_doc_index.py --load <biencoder_ckpt> \
      --evidence_data_path psgs_w100.tsv --embedding_path evidence.npz \
      --tokenizer_type BertWordPieceLowerCase --vocab_file vocab.txt

Multi-host: run one process per shard with --shard i --num_shards N, then
merge with --merge.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform


def main(argv=None):
    ensure_env_platform()
    p = argparse.ArgumentParser("create_doc_index", description=__doc__)
    p.add_argument("--load", required=True,
                   help="biencoder checkpoint root")
    p.add_argument("--evidence_data_path", required=True)
    p.add_argument("--embedding_path", required=True)
    p.add_argument("--tokenizer_type", default="BertWordPieceLowerCase")
    p.add_argument("--vocab_file", default=None)
    p.add_argument("--merge_file", default=None)
    p.add_argument("--tokenizer_model", default=None)
    p.add_argument("--retriever_seq_length", type=int, default=256)
    p.add_argument("--indexer_batch_size", type=int, default=128)
    p.add_argument("--indexer_log_interval", type=int, default=10)
    p.add_argument("--ict_head_size", type=int, default=128)
    p.add_argument("--biencoder_shared_query_context_model",
                   action="store_true")
    p.add_argument("--shard", type=int, default=0)
    p.add_argument("--num_shards", type=int, default=1)
    p.add_argument("--merge", action="store_true",
                   help="merge shard files written by previous runs and "
                        "exit")
    # model shape fallback when the checkpoint has no config
    p.add_argument("--num_layers", type=int, default=12)
    p.add_argument("--hidden_size", type=int, default=768)
    p.add_argument("--num_attention_heads", type=int, default=12)
    args = p.parse_args(argv)

    from megatron_tpu.data.realm_index import OpenRetrievalDataStore

    if args.merge:
        store = OpenRetrievalDataStore(args.embedding_path,
                                       load_from_path=False)
        store.merge_shards_and_save()
        print(f"merged {len(store)} block embeddings -> "
              f"{args.embedding_path}")
        return 0

    from megatron_tpu.data.orqa_dataset import OpenRetrievalEvidenceDataset
    from megatron_tpu.data.tokenizers import build_tokenizer
    from megatron_tpu.indexer import IndexBuilder
    from tasks.main import load_biencoder

    tokenizer = build_tokenizer(
        args.tokenizer_type, vocab_file=args.vocab_file,
        merge_file=args.merge_file, tokenizer_model=args.tokenizer_model)
    params, mcfg = load_biencoder(args, tokenizer.vocab_size,
                                  args.retriever_seq_length)
    evidence = OpenRetrievalEvidenceDataset(
        args.evidence_data_path, tokenizer, args.retriever_seq_length)
    builder = IndexBuilder(
        params, mcfg, evidence, embedding_path=args.embedding_path,
        batch_size=args.indexer_batch_size, shard=args.shard,
        num_shards=args.num_shards,
        log_interval=args.indexer_log_interval)
    store = builder.build_and_save_index()
    print(f"indexed {len(store)} evidence blocks"
          + (f" (shard {args.shard}/{args.num_shards})"
             if args.num_shards > 1 else ""))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
