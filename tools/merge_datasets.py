"""Merge multiple indexed datasets into one.

TPU-native port of /root/reference/tools/merge_datasets.py: concatenates all
`*_document.bin/.idx` pairs under --input into a single indexed dataset.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.data.indexed_dataset import (IndexedDatasetBuilder,
                                               MMapIndexedDataset)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--input", type=str, required=True,
                   help="directory containing .bin/.idx pairs")
    p.add_argument("--output_prefix", type=str, required=True)
    args = p.parse_args(argv)

    prefixes = sorted(
        os.path.join(args.input, f[:-4])
        for f in os.listdir(args.input)
        if f.endswith(".idx")
        and os.path.exists(os.path.join(args.input, f[:-4] + ".bin")))
    assert prefixes, f"no .bin/.idx pairs in {args.input}"

    first = MMapIndexedDataset(prefixes[0])
    builder = IndexedDatasetBuilder(args.output_prefix, dtype=first.dtype)
    for prefix in prefixes:
        print(f"merging {prefix}")
        builder.merge_file(prefix)
    builder.finalize()
    out = MMapIndexedDataset(args.output_prefix)
    print(f"done: {len(out)} sequences, "
          f"{int(out.sizes.sum())} tokens -> {args.output_prefix}.bin/.idx")


if __name__ == "__main__":
    main()
