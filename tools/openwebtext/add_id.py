"""Add sequential ids to a jsonl corpus.

Counterpart of ref: tools/openwebtext/add_id.py — each record gains
{"id": "<prefix>-<n>"} (prefix via --id_prefix).

Usage: python add_id.py --input_file in.jsonl --output_file out.jsonl
           [--id_prefix corpusname]
"""
from __future__ import annotations

import argparse
import json

try:
    from tools.openwebtext.owt_utils import iter_jsonl
except ImportError:  # direct script execution
    from owt_utils import iter_jsonl


def add_ids(input_path: str, output_path: str, prefix: str = "") -> int:
    n = 0
    with open(output_path, "w", encoding="utf-8") as out:
        for i, rec in enumerate(iter_jsonl(input_path)):
            rec["id"] = f"{prefix}-{i}" if prefix else str(i)
            out.write(json.dumps(rec, ensure_ascii=False) + "\n")
            n += 1
    return n


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--input_file", required=True)
    p.add_argument("--output_file", required=True)
    p.add_argument("--id_prefix", default="")
    args = p.parse_args(argv)
    n = add_ids(args.input_file, args.output_file, args.id_prefix)
    print(f"add_id: {n} records")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
