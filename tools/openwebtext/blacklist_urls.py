"""Filter a URL list against domain/extension blacklists.

Counterpart of ref: tools/openwebtext/blacklist_urls.py — same contract
(input: files of one URL per line, output: the clean URLs), same filter
axes: blacklisted registered domains (media/social/commerce hosts whose
pages are not prose), blacklisted path extensions (binary/media files),
malformed or overlong URLs. The domain list ships as a starter set and
extends via --domain_blacklist_file (the reference hardcodes ~200 domains;
the mechanism, not the list, is the tool).

Usage: python blacklist_urls.py <url_file_or_dir> <clean_urls_out>
"""
from __future__ import annotations

import glob
import os
import sys

try:
    from tools.openwebtext.owt_utils import registered_domain, url_extension
except ImportError:  # direct script execution
    from owt_utils import registered_domain, url_extension

DOMAIN_BLACKLIST = frozenset((
    # media/image/video hosts
    "imgur", "giphy", "gfycat", "flickr", "youtube", "youtu", "vimeo",
    "dailymotion", "liveleak", "imageshack", "imgflip", "gyazo",
    "deviantart", "artstation", "bandcamp", "soundcloud", "spotify",
    # social / chat
    "facebook", "fbcdn", "instagram", "twitter", "discord", "discordapp",
    "reddit", "redd", "snapchat", "pinterest", "tumblr",
    # commerce / apps
    "amazon", "ebay", "etsy", "apple", "google", "play", "steampowered",
    "twitch", "patreon", "paypal", "kickstarter",
    # infra / shorteners / misc non-prose
    "github", "dropbox", "akamaihd", "cloudfront", "bit", "goo", "tinyurl",
    "lmgtfy", "archive", "webcache", "wikimedia", "wiktionary",
))

EXTENSION_BLACKLIST = frozenset((
    "jpg", "jpeg", "png", "gif", "bmp", "webp", "svg", "ico", "tif",
    "mp3", "wav", "ogg", "flac", "mp4", "avi", "mkv", "webm", "mov",
    "pdf", "zip", "rar", "gz", "tar", "7z", "exe", "apk", "dmg", "iso",
    "css", "js", "xml", "rss", "atom",
))

MAX_URL_LEN = 500


def url_ok(url: str, domain_blacklist=DOMAIN_BLACKLIST,
           extension_blacklist=EXTENSION_BLACKLIST) -> bool:
    url = url.strip()
    if not url or len(url) > MAX_URL_LEN or " " in url:
        return False
    if not (url.startswith("http://") or url.startswith("https://")):
        return False
    if registered_domain(url) in domain_blacklist:
        return False
    if url_extension(url) in extension_blacklist:
        return False
    return True


def filter_urls(input_path: str, output_path: str,
                domain_blacklist_file: str | None = None) -> tuple:
    """Returns (kept, dropped)."""
    domains = set(DOMAIN_BLACKLIST)
    if domain_blacklist_file:
        with open(domain_blacklist_file) as f:
            domains.update(line.strip().lower() for line in f
                           if line.strip())
    paths = (sorted(glob.glob(os.path.join(input_path, "*")))
             if os.path.isdir(input_path) else [input_path])
    kept = dropped = 0
    with open(output_path, "w") as out:
        for path in paths:
            with open(path, errors="ignore") as f:
                for line in f:
                    url = line.strip()
                    if url_ok(url, domains):
                        out.write(url + "\n")
                        kept += 1
                    elif url:
                        dropped += 1
    return kept, dropped


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    assert len(argv) >= 2, __doc__
    kept, dropped = filter_urls(argv[0], argv[1],
                                argv[2] if len(argv) > 2 else None)
    print(f"blacklist_urls: kept {kept}, dropped {dropped}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
