"""Clean a loose-json corpus: fix text, keep English, drop short docs.

Counterpart of ref: tools/openwebtext/cleanup_dataset.py — same jsonl
contract ({"text": ..., "url": ...} per line) and the same three filters:
text repair (ftfy there, owt_utils.fix_text here), language detection
(langdetect there, a stopword/ascii heuristic here), and a minimum token
count (128 GPT-2-ish tokens; whitespace tokens are used when no tokenizer
is given, with the same 8-chars-per-token prefilter shortcut).

Usage: python cleanup_dataset.py <input.jsonl> <output.jsonl>
"""
from __future__ import annotations

import sys

try:
    from tools.openwebtext.owt_utils import (fix_text, iter_jsonl,
                                             looks_english)
except ImportError:  # direct script execution
    from owt_utils import (fix_text, iter_jsonl,
                                looks_english)

MIN_DOCUMENT_TOKENS = 128


def clean_corpus(input_path: str, output_path: str, *,
                 min_tokens: int = MIN_DOCUMENT_TOKENS,
                 tokenize=None) -> dict:
    """Returns counters {docs, written, fixed, non_english, small}."""
    tokenize = tokenize or (lambda t: t.split())
    stats = dict(docs=0, written=0, fixed=0, non_english=0, small=0)
    import json
    with open(output_path, "w", encoding="utf-8") as out:
        for rec in iter_jsonl(input_path):
            stats["docs"] += 1
            text = rec.get("text", "")
            fixed = fix_text(text)
            if fixed != text:
                stats["fixed"] += 1
            rec["text"] = fixed
            if not looks_english(fixed):
                stats["non_english"] += 1
                continue
            # ~8 chars/token upper bound: only tokenize docs short enough
            # to possibly fail the cutoff (ref: cleanup_dataset.py:63-70)
            if len(fixed) < 8 * min_tokens and \
                    len(tokenize(fixed)) < min_tokens:
                stats["small"] += 1
                continue
            out.write(json.dumps(rec, ensure_ascii=False) + "\n")
            stats["written"] += 1
    return stats


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    assert len(argv) >= 2, __doc__
    stats = clean_corpus(argv[0], argv[1])
    print("cleanup_dataset:", stats)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
