"""Task-selectable additional corpus cleaning.

Counterpart of ref: tools/openwebtext/cleanup_fix_dataset.py — the same
named tasks applied per doc, with kept docs to one file and filtered docs
to another:

- remove_512: drop docs under 512 characters
- remove_256_javascript: drop short docs that mention javascript (boiler
  plate "enable javascript" shells)
- remove_512_non_english: drop short non-English docs
- ftfy_fix_text: repair mojibake/control chars in place
- general_cleaning: collapse whitespace runs, strip null bytes and
  repeated punctuation runs

Usage: python cleanup_fix_dataset.py --input_files a.jsonl [b.jsonl ...]
           --output_file kept.jsonl --filtered_file dropped.jsonl
           --tasks remove_512 ftfy_fix_text ...
"""
from __future__ import annotations

import argparse
import json
import re

try:
    from tools.openwebtext.owt_utils import (fix_text, iter_jsonl,
                                             looks_english)
except ImportError:  # direct script execution
    from owt_utils import (fix_text, iter_jsonl,
                                looks_english)

TASKS = ("remove_512", "remove_256_javascript", "remove_512_non_english",
         "ftfy_fix_text", "general_cleaning")

_WS_RUN = re.compile(r"[ \t]{3,}")
_NL_RUN = re.compile(r"\n{4,}")
_PUNCT_RUN = re.compile(r"([!?.,-])\1{4,}")


def process_doc(rec: dict, tasks) -> tuple:
    """-> (rec, drop_reason or None)."""
    text = rec.get("text", "")
    if "remove_512" in tasks and len(text) < 512:
        return rec, "remove_512"
    if "remove_256_javascript" in tasks and len(text) < 256 and \
            "javascript" in text.lower():
        return rec, "remove_256_javascript"
    if "remove_512_non_english" in tasks and len(text) < 512 and \
            not looks_english(text):
        return rec, "remove_512_non_english"
    if "ftfy_fix_text" in tasks:
        rec["text"] = text = fix_text(text)
    if "general_cleaning" in tasks:
        text = text.replace("\x00", "")
        text = _WS_RUN.sub(" ", text)
        text = _NL_RUN.sub("\n\n\n", text)
        text = _PUNCT_RUN.sub(r"\1\1\1", text)
        rec["text"] = text
    return rec, None


def process_files(input_files, output_file, filtered_file, tasks) -> dict:
    stats = {t: 0 for t in tasks}
    stats.update(docs=0, written=0)
    with open(output_file, "w", encoding="utf-8") as kept, \
            open(filtered_file, "w", encoding="utf-8") as dropped:
        for path in input_files:
            for rec in iter_jsonl(path):
                stats["docs"] += 1
                rec, reason = process_doc(rec, tasks)
                line = json.dumps(rec, ensure_ascii=False) + "\n"
                if reason is None:
                    kept.write(line)
                    stats["written"] += 1
                else:
                    dropped.write(line)
                    stats[reason] += 1
    return stats


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--input_files", nargs="+", required=True)
    p.add_argument("--output_file", required=True)
    p.add_argument("--filtered_file", required=True)
    p.add_argument("--tasks", nargs="+", default=list(TASKS),
                   choices=list(TASKS))
    args = p.parse_args(argv)
    stats = process_files(args.input_files, args.output_file,
                          args.filtered_file, args.tasks)
    print("cleanup_fix_dataset:", stats)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
