"""Deduplicate downstream-task n-grams out of a training corpus.

Counterpart of ref: tools/openwebtext/filter_ngrams.py — task
decontamination by 13-gram matching: build the n-gram set from the task
data (lambada / squad / generic jsonl), scan each training document with a
sliding word window, and on a match cut the n-gram plus 200 characters on
each side. Split fragments shorter than 200 characters are dropped, and a
document that splits more than 10 times is dropped entirely
(ref: filter_ngrams.py:323-398 and the --max_ngram_size /
--filter_text_char_len / --splits_count / --remove_char_each_side knobs).

Usage: python filter_ngrams.py --tasks lambada --lambada_path test.jsonl
           --dedup_dataset train.jsonl text --output clean.jsonl
"""
from __future__ import annotations

import argparse
import json
import re
from typing import List, Set, Tuple

try:
    from tools.openwebtext.owt_utils import iter_jsonl
except ImportError:  # direct script execution
    from owt_utils import iter_jsonl

_WORD = re.compile(r"[a-z0-9']+")


def _words_with_spans(text: str) -> Tuple[List[str], List[Tuple[int, int]]]:
    words, spans = [], []
    for m in _WORD.finditer(text.lower()):
        words.append(m.group())
        spans.append((m.start(), m.end()))
    return words, spans


def ngrams_of(text: str, n: int) -> Set[tuple]:
    words, _ = _words_with_spans(text)
    return {tuple(words[i:i + n]) for i in range(len(words) - n + 1)}


def task_ngrams(task: str, path: str, n: int, key: str = "text"
                ) -> Set[tuple]:
    """Task file -> n-gram set. lambada: jsonl with 'text'; squad: the
    official nested json (questions + answer texts); generic: jsonl with
    `key` (ref: filter_ngrams.py:189-264 process_task_lambda/process_task)."""
    grams: Set[tuple] = set()
    if task == "squad":
        with open(path, encoding="utf-8") as f:
            data = json.load(f)["data"]
        for article in data:
            for para in article["paragraphs"]:
                for qa in para["qas"]:
                    grams |= ngrams_of(qa["question"], n)
                    for ans in qa.get("answers", []):
                        grams |= ngrams_of(ans["text"], n)
    else:  # lambada and generic jsonl tasks
        for rec in iter_jsonl(path):
            grams |= ngrams_of(rec.get(key, ""), n)
    return grams


def split_document(text: str, grams: Set[tuple], *, n: int,
                   pad_chars: int, min_chars: int) -> Tuple[List[str], int]:
    """-> (clean fragments, match count). Matched n-grams are removed with
    `pad_chars` characters on each side; fragments under `min_chars` are
    dropped."""
    words, spans = _words_with_spans(text)
    matches = []
    i = 0
    while i <= len(words) - n:
        if tuple(words[i:i + n]) in grams:
            lo = max(spans[i][0] - pad_chars, 0)
            hi = min(spans[i + n - 1][1] + pad_chars, len(text))
            if matches and lo <= matches[-1][1]:
                matches[-1] = (matches[-1][0], hi)
            else:
                matches.append((lo, hi))
            i += n
        else:
            i += 1
    if not matches:
        return [text], 0
    pieces, pos = [], 0
    for lo, hi in matches:
        pieces.append(text[pos:lo])
        pos = hi
    pieces.append(text[pos:])
    return [p for p in pieces if len(p) >= min_chars], len(matches)


def filter_corpus(dedup_path: str, text_key: str, output_path: str,
                  grams: Set[tuple], *, n: int = 13,
                  pad_chars: int = 200, min_chars: int = 200,
                  max_splits: int = 10) -> dict:
    stats = dict(docs=0, written=0, split=0, dropped=0, trimmed=0)
    with open(output_path, "w", encoding="utf-8") as out:
        for rec in iter_jsonl(dedup_path):
            stats["docs"] += 1
            pieces, n_matches = split_document(
                rec.get(text_key, ""), grams, n=n, pad_chars=pad_chars,
                min_chars=min_chars)
            if n_matches == 0:
                out.write(json.dumps(rec, ensure_ascii=False) + "\n")
                stats["written"] += 1
                continue
            if len(pieces) > max_splits or not pieces:
                stats["dropped"] += 1
                continue
            stats["split"] += 1
            stats["trimmed"] += n_matches
            for j, piece in enumerate(pieces):
                frag = dict(rec)
                frag[text_key] = piece
                if len(pieces) > 1:
                    frag["split_part"] = j
                out.write(json.dumps(frag, ensure_ascii=False) + "\n")
                stats["written"] += 1
    return stats


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--tasks", nargs="+", required=True,
                   help="lambada, squad, or generic jsonl paths via "
                        "--task_files")
    p.add_argument("--lambada_path")
    p.add_argument("--squad_path")
    p.add_argument("--task_files", nargs="*", default=[],
                   help="jsonl files for generic tasks (text key)")
    p.add_argument("--dedup_dataset", nargs=2, required=True,
                   metavar=("FILE", "KEY"))
    p.add_argument("--output", required=True)
    p.add_argument("--max_ngram_size", type=int, default=13)
    p.add_argument("--filter_text_char_len", type=int, default=200)
    p.add_argument("--splits_count", type=int, default=10)
    p.add_argument("--remove_char_each_side", type=int, default=200)
    args = p.parse_args(argv)

    grams: Set[tuple] = set()
    for task in args.tasks:
        if task == "lambada":
            assert args.lambada_path, "--lambada_path required"
            grams |= task_ngrams("lambada", args.lambada_path,
                                 args.max_ngram_size)
        elif task == "squad":
            assert args.squad_path, "--squad_path required"
            grams |= task_ngrams("squad", args.squad_path,
                                 args.max_ngram_size)
        else:
            for path in args.task_files:
                grams |= task_ngrams(task, path, args.max_ngram_size)
    print(f"filter_ngrams: {len(grams)} task {args.max_ngram_size}-grams")
    stats = filter_corpus(
        args.dedup_dataset[0], args.dedup_dataset[1], args.output, grams,
        n=args.max_ngram_size, pad_chars=args.remove_char_each_side,
        min_chars=args.filter_text_char_len, max_splits=args.splits_count)
    print("filter_ngrams:", stats)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
