"""Find near-duplicate documents via minhash LSH.

Counterpart of ref: tools/openwebtext/find_duplicates.py — same contract:
inputs are (jsonl, url_key) pairs, output is jsonl of
{main_url: [{other_url: jaccard}, ...]} candidate-duplicate records for
group_duplicate_url.py. The minhash fingerprints + banded LSH buckets come
from owt_utils (the reference uses the external mattilyra/LSH package);
bucket members are then verified with exact shingle jaccard, same
main-vs-rest sweep semantics (ref: find_duplicates.py:44-78).

Usage: python find_duplicates.py --inputs a.jsonl url [b.jsonl url2 ...]
           --output dups.jsonl [--jaccard union|min|max] [--threshold 0.5]
"""
from __future__ import annotations

import argparse
import json

import numpy as np

try:
    from tools.openwebtext.owt_utils import (LshIndex, MinHasher, iter_jsonl,
                                             jaccard, shingles)
except ImportError:  # direct script execution
    from owt_utils import (LshIndex, MinHasher, iter_jsonl,
                                jaccard, shingles)


def find_duplicates(inputs, output_path, *, jaccard_mode: str = "union",
                    threshold: float = 0.5, num_perm: int = 128,
                    num_bands: int = 16, char_ngram: int = 5,
                    seed: int = 1234) -> int:
    """Returns the number of detected duplicate urls."""
    hasher = MinHasher(num_perm=num_perm, char_ngram=char_ngram, seed=seed)
    index = LshIndex(num_perm=num_perm, num_bands=num_bands)
    url_doc: dict = {}
    for path, key in inputs:
        for rec in iter_jsonl(path):
            url, text = rec.get(key), rec.get("text", "")
            if url is None or url in url_doc:
                continue
            url_doc[url] = text
            index.add(url, hasher.fingerprint(text))

    rng = np.random.default_rng(seed)
    removed: set = set()
    n_dup = 0
    shingle_cache: dict = {}

    def doc_shingles(url):
        # memoized: a url can appear in buckets of many bands and many
        # sweep rounds; recomputing multi-KB shingle sets would dominate
        if url not in shingle_cache:
            shingle_cache[url] = shingles(url_doc[url], char_ngram)
        return shingle_cache[url]

    with open(output_path, "w", encoding="utf-8") as out:
        for members in index.candidate_buckets():
            bucket = [u for u in members if u not in removed]
            # main-vs-rest sweep: pick a random main url, claim everything
            # similar to it, repeat on the remainder
            while len(bucket) > 1:
                main = bucket[int(rng.integers(len(bucket)))]
                main_sh = doc_shingles(main)
                claimed = []
                rest = []
                for other in bucket:
                    if other == main:
                        continue
                    sim = jaccard(main_sh, doc_shingles(other),
                                  jaccard_mode)
                    if sim > threshold:
                        claimed.append({other: round(sim, 4)})
                        removed.add(other)
                        n_dup += 1
                    else:
                        rest.append(other)
                if claimed:
                    out.write(json.dumps({main: claimed},
                                         ensure_ascii=False) + "\n")
                bucket = rest
    return n_dup


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--inputs", nargs="+", required=True,
                   help="alternating: file1 key1 [file2 key2 ...]")
    p.add_argument("--output", required=True)
    p.add_argument("--jaccard", default="union",
                   choices=["union", "min", "max"])
    p.add_argument("--threshold", type=float, default=0.5)
    p.add_argument("--num_perm", type=int, default=128)
    p.add_argument("--num_bands", type=int, default=16)
    p.add_argument("--seed", type=int, default=1234)
    args = p.parse_args(argv)
    assert len(args.inputs) % 2 == 0, "--inputs wants file/key pairs"
    pairs = list(zip(args.inputs[::2], args.inputs[1::2]))
    n = find_duplicates(pairs, args.output, jaccard_mode=args.jaccard,
                        threshold=args.threshold, num_perm=args.num_perm,
                        num_bands=args.num_bands, seed=args.seed)
    print(f"find_duplicates: {n} duplicate urls")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
