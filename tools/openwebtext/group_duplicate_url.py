"""Group candidate-duplicate URLs into connected components.

Counterpart of ref: tools/openwebtext/group_duplicate_url.py — reads
find_duplicates.py's {main: [{other: jaccard}, ...]} records, keeps edges
at or above the similarity threshold, and unions them into groups; output
is one json list of urls per group (the first url is the keeper).

Usage: python group_duplicate_url.py <dups.jsonl> <groups.jsonl> [thresh]
"""
from __future__ import annotations

import json
import sys

try:
    from tools.openwebtext.owt_utils import iter_jsonl
except ImportError:  # direct script execution
    from owt_utils import iter_jsonl


class _UnionFind:
    def __init__(self):
        self.parent: dict = {}

    def find(self, x):
        self.parent.setdefault(x, x)
        while self.parent[x] != x:
            self.parent[x] = self.parent[self.parent[x]]
            x = self.parent[x]
        return x

    def union(self, a, b):
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.parent[rb] = ra


def group_urls(input_path: str, output_path: str,
               threshold: float = 0.7) -> int:
    """Returns the number of groups written."""
    uf = _UnionFind()
    for rec in iter_jsonl(input_path):
        for main, others in rec.items():
            for entry in others:
                for other, sim in entry.items():
                    if sim >= threshold:
                        uf.union(main, other)
    groups: dict = {}
    for url in list(uf.parent):
        groups.setdefault(uf.find(url), []).append(url)
    n = 0
    with open(output_path, "w", encoding="utf-8") as out:
        for root, members in groups.items():
            if len(members) > 1:
                ordered = [root] + [u for u in sorted(members)
                                    if u != root]
                out.write(json.dumps(ordered, ensure_ascii=False) + "\n")
                n += 1
    return n


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    assert len(argv) >= 2, __doc__
    thresh = float(argv[2]) if len(argv) > 2 else 0.7
    n = group_urls(argv[0], argv[1], thresh)
    print(f"group_duplicate_url: {n} groups")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
