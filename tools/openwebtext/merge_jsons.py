"""Merge every *.json / *.jsonl file in a directory into one jsonl file.

Counterpart of ref: tools/openwebtext/merge_jsons.py.

Usage: python merge_jsons.py --json_path <dir> --output_file merged.jsonl
"""
from __future__ import annotations

import argparse
import glob
import json
import os

try:
    from tools.openwebtext.owt_utils import iter_jsonl
except ImportError:  # direct script execution
    from owt_utils import iter_jsonl


def merge(json_path: str, output_file: str) -> int:
    files = sorted(glob.glob(os.path.join(json_path, "*.json"))
                   + glob.glob(os.path.join(json_path, "*.jsonl")))
    n = 0
    with open(output_file, "w", encoding="utf-8") as out:
        for path in files:
            if os.path.abspath(path) == os.path.abspath(output_file):
                continue
            for rec in iter_jsonl(path):
                out.write(json.dumps(rec, ensure_ascii=False) + "\n")
                n += 1
    return n


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--json_path", default=".")
    p.add_argument("--output_file", default="merged_output.jsonl")
    args = p.parse_args(argv)
    n = merge(args.json_path, args.output_file)
    print(f"merge_jsons: {n} records")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
