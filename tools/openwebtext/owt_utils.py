"""Shared helpers for the openwebtext corpus-cleaning suite.

Self-contained stand-ins for the reference suite's external dependencies
(ref: tools/openwebtext/README.md lists ftfy, langdetect, tldextract and
the mattilyra/LSH minhash package — none are vendored here):

- `fix_text`: the high-frequency subset of ftfy's repairs — mojibake from
  latin-1/cp1252 round-trips, unicode NFC normalization, control-char and
  stray-BOM removal.
- `looks_english`: a stopword-hit-rate + ascii-ratio heuristic in place of
  langdetect (the corpus filter only needs a coarse en/non-en split).
- `registered_domain`: urlparse + public-suffix-ish heuristics in place of
  tldextract.
- `MinHasher` / `LshIndex`: numpy minhash fingerprints + banded LSH
  buckets, the same candidate-generation scheme as the reference's lsh
  package (ref: find_duplicates.py:34-41,150-200).
"""
from __future__ import annotations

import hashlib
import json
import re
import unicodedata
from typing import Iterable, Iterator, List, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# text repair / language heuristics
# ---------------------------------------------------------------------------

_MOJIBAKE = {
    "â": "'", "â": "'",
    "â": '"', "â": '"',
    "â": "–", "â": "—",
    "â¦": "…",
    "Ã©": "é", "Ã¨": "è",
    "Ã¡": "á", "Ã³": "ó",
    "Ãº": "ú", "Ã±": "ñ",
    "Â ": " ",
}
_CTRL = re.compile(r"[\x00-\x08\x0b\x0c\x0e-\x1f\x7f﻿]")


def fix_text(text: str) -> str:
    """Light ftfy: undo common cp1252 mojibake, normalize to NFC, strip
    control characters and BOMs."""
    if any(k in text for k in _MOJIBAKE):
        for bad, good in _MOJIBAKE.items():
            text = text.replace(bad, good)
    # full round-trip repair when the text looks double-encoded: cp1252
    # first (the visible "â€™"-style mojibake: € and ™ are cp1252-only),
    # then latin-1 (the raw \x80-\x9f control variant)
    for enc in ("cp1252", "latin-1"):
        try:
            candidate = text.encode(enc).decode("utf-8")
        except (UnicodeDecodeError, UnicodeEncodeError):
            continue
        if candidate.count("�") == 0 and len(candidate) < len(text):
            text = candidate
            break
    text = unicodedata.normalize("NFC", text)
    return _CTRL.sub("", text)


_STOPWORDS = frozenset(
    "the of and to in a is that it for on as with was at by an be this "
    "have from or had not are but they you we his her she he will which "
    "their all there been one can more has when who what about if out so "
    "up said do its".split())


def looks_english(text: str, min_stopword_rate: float = 0.08,
                  min_ascii_rate: float = 0.7) -> bool:
    """Coarse English detector: enough ascii letters AND enough common
    English stopwords among the words."""
    if not text:
        return False
    sample = text[:4000]
    ascii_rate = sum(c.isascii() for c in sample) / len(sample)
    if ascii_rate < min_ascii_rate:
        return False
    words = re.findall(r"[a-zA-Z']+", sample.lower())
    if len(words) < 5:
        return False
    hits = sum(w in _STOPWORDS for w in words)
    return hits / len(words) >= min_stopword_rate


# ---------------------------------------------------------------------------
# URLs
# ---------------------------------------------------------------------------

_TWO_LEVEL_SUFFIXES = frozenset(
    ("co.uk", "org.uk", "ac.uk", "gov.uk", "com.au", "net.au", "org.au",
     "co.jp", "co.in", "co.nz", "com.br", "com.cn", "com.mx", "co.za"))


def registered_domain(url: str) -> str:
    """Second-level domain of a URL ('https://a.b.example.co.uk/x' ->
    'example') — the tldextract.domain equivalent the blacklist keys on."""
    from urllib.parse import urlparse
    host = urlparse(url if "//" in url else "//" + url).hostname or ""
    parts = host.lower().split(".")
    if len(parts) < 2:
        return host.lower()
    if len(parts) >= 3 and ".".join(parts[-2:]) in _TWO_LEVEL_SUFFIXES:
        return parts[-3]
    return parts[-2]


def url_extension(url: str) -> str:
    from urllib.parse import urlparse
    path = urlparse(url if "//" in url else "//" + url).path
    dot = path.rfind(".")
    return path[dot + 1:].lower() if dot >= 0 else ""


# ---------------------------------------------------------------------------
# jsonl IO
# ---------------------------------------------------------------------------

def iter_jsonl(path: str) -> Iterator[dict]:
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    continue


def write_jsonl(path: str, records: Iterable[dict]) -> int:
    n = 0
    with open(path, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec, ensure_ascii=False) + "\n")
            n += 1
    return n


# ---------------------------------------------------------------------------
# minhash LSH
# ---------------------------------------------------------------------------

def shingles(text: str, char_ngram: int = 5) -> set:
    """Character n-gram shingle set (ref: find_duplicates.py:13-15)."""
    return {text[i:i + char_ngram]
            for i in range(max(len(text) - char_ngram + 1, 1))}


def jaccard(a: set, b: set, mode: str = "union") -> float:
    if not a or not b:
        return 0.0
    inter = len(a & b)
    if mode == "min":
        return inter / min(len(a), len(b))
    if mode == "max":
        return inter / max(len(a), len(b))
    return inter / len(a | b)


_MERSENNE = (1 << 61) - 1


class MinHasher:
    """Minhash fingerprints over character shingles: k universal-hash
    permutations a*x+b mod p, minimum per permutation."""

    def __init__(self, num_perm: int = 128, char_ngram: int = 5,
                 seed: int = 1234):
        rng = np.random.default_rng(seed)
        self.a = rng.integers(1, _MERSENNE, size=num_perm, dtype=np.int64)
        self.b = rng.integers(0, _MERSENNE, size=num_perm, dtype=np.int64)
        self.char_ngram = char_ngram
        self.num_perm = num_perm

    def fingerprint(self, text: str) -> np.ndarray:
        hashes = np.fromiter(
            (int.from_bytes(
                hashlib.blake2b(s.encode("utf-8", "ignore"),
                                digest_size=8).digest(), "big")
             for s in shingles(text, self.char_ngram)),
            dtype=np.uint64)
        if hashes.size == 0:
            return np.zeros(self.num_perm, np.uint64)
        x = hashes.astype(np.int64)[:, None]
        hv = (self.a[None, :] * x + self.b[None, :]) % _MERSENNE
        return hv.min(axis=0).astype(np.uint64)


class LshIndex:
    """Banded LSH over minhash fingerprints: keys whose fingerprints agree
    on all rows of any band land in the same bucket -> candidate pairs."""

    def __init__(self, num_perm: int = 128, num_bands: int = 16):
        assert num_perm % num_bands == 0
        self.num_bands = num_bands
        self.rows = num_perm // num_bands
        self.buckets: List[dict] = [{} for _ in range(num_bands)]

    def add(self, key, fingerprint: np.ndarray) -> None:
        for band in range(self.num_bands):
            sig = fingerprint[band * self.rows:(band + 1) * self.rows]
            self.buckets[band].setdefault(sig.tobytes(), []).append(key)

    def candidate_buckets(self) -> Iterator[List]:
        for band_buckets in self.buckets:
            for members in band_buckets.values():
                if len(members) > 1:
                    yield members
