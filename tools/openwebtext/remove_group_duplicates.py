"""Drop all but the first URL of every duplicate group from a corpus.

Counterpart of ref: tools/openwebtext/remove_group_duplicates.py — reads
group_duplicate_url.py's per-group url lists (keeper first), builds the
removal set from positions 1.., and streams the corpus through.

Usage: python remove_group_duplicates.py <groups.jsonl> <corpus.jsonl>
           <deduped.jsonl>
"""
from __future__ import annotations

import json
import sys

try:
    from tools.openwebtext.owt_utils import iter_jsonl
except ImportError:  # direct script execution
    from owt_utils import iter_jsonl


def remove_duplicates(groups_path: str, corpus_path: str,
                      output_path: str, url_key: str = "url") -> tuple:
    """Returns (written, removed)."""
    remove: set = set()
    with open(groups_path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            urls = json.loads(line)
            remove.update(urls[1:])
    written = removed = 0
    with open(output_path, "w", encoding="utf-8") as out:
        for rec in iter_jsonl(corpus_path):
            if rec.get(url_key) in remove:
                removed += 1
                continue
            out.write(json.dumps(rec, ensure_ascii=False) + "\n")
            written += 1
    return written, removed


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    assert len(argv) >= 3, __doc__
    written, removed = remove_duplicates(argv[0], argv[1], argv[2])
    print(f"remove_group_duplicates: wrote {written}, removed {removed}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
