"""Preprocess jsonl corpora into the indexed .bin/.idx format.

TPU-native port of the reference's preprocessing tool
(ref: /root/reference/tools/preprocess_data.py:42-201): jsonl in, one
tokenized document per json line, optional EOD append, multiprocess encoding,
indexed-dataset output. Same CLI surface where it matters
(--input/--json_keys/--output_prefix/--tokenizer_type/--append_eod/--workers).
"""
from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.data.indexed_dataset import (IndexedDatasetBuilder,
                                               best_fitting_dtype)
from megatron_tpu.data.tokenizers import build_tokenizer

_tok = None
_args = None


def _init_worker(args):
    global _tok, _args
    _args = args
    _tok = build_tokenizer(
        args.tokenizer_type, vocab_file=args.vocab_file,
        merge_file=args.merge_file, tokenizer_model=args.tokenizer_model,
        vocab_extra_ids=args.vocab_extra_ids)


def _encode(line: str):
    """(ref: tools/preprocess_data.py Encoder.encode)"""
    line = line.strip()
    if not line:
        return None, 0
    data = json.loads(line)
    out = {}
    for key in _args.json_keys:
        text = data[key]
        ids = _tok.tokenize(text)
        if _args.append_eod and ids:
            ids.append(_tok.eod)
        out[key] = ids
    return out, len(line)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--input", type=str, required=True)
    p.add_argument("--json_keys", nargs="+", default=["text"])
    p.add_argument("--output_prefix", type=str, required=True)
    p.add_argument("--tokenizer_type", type=str,
                   default="SentencePieceTokenizer")
    p.add_argument("--vocab_file", type=str, default=None)
    p.add_argument("--merge_file", type=str, default=None)
    p.add_argument("--tokenizer_model", type=str, default=None)
    p.add_argument("--vocab_extra_ids", type=int, default=0)
    p.add_argument("--append_eod", action="store_true")
    p.add_argument("--workers", type=int, default=1)
    p.add_argument("--log_interval", type=int, default=10000)
    args = p.parse_args(argv)

    _init_worker(args)
    vocab_size = _tok.vocab_size
    dtype = best_fitting_dtype(vocab_size)

    builders = {
        key: IndexedDatasetBuilder(
            f"{args.output_prefix}_{key}_document"
            if len(args.json_keys) > 1 else f"{args.output_prefix}_document",
            dtype=dtype)
        for key in args.json_keys
    }

    t0 = time.time()
    n = 0
    total_bytes = 0

    def consume(encoded):
        nonlocal n, total_bytes
        for doc, nbytes in encoded:
            total_bytes += nbytes
            if doc is None:
                continue
            for key, ids in doc.items():
                if ids:
                    builders[key].add_item(ids)
                    builders[key].end_document()
            n += 1
            if n % args.log_interval == 0:
                mbs = total_bytes / 1e6 / (time.time() - t0)
                print(f"processed {n} documents ({mbs:.1f} MB/s)")

    with open(args.input, encoding="utf-8") as f:
        if args.workers > 1:
            with mp.Pool(args.workers, initializer=_init_worker,
                         initargs=(args,)) as pool:
                consume(pool.imap(_encode, f, chunksize=32))
        else:
            consume(map(_encode, f))
    for b in builders.values():
        b.finalize()
    print(f"done: {n} documents in {time.time()-t0:.1f}s "
          f"-> {args.output_prefix}*.bin/.idx (dtype {dtype})")


if __name__ == "__main__":
    main()
