#!/bin/bash
# Round-5 probe-and-fire loop: probe the axon TPU tunnel; the moment a
# window opens, run bench.py (main record + extras chain = the
# PERF_NOTES pending queue). Logs to /tmp/onchip_r5/. Detach with:
#   nohup bash tools/probe_and_fire.sh >/tmp/tpu_probe_loop_r5.log 2>&1 &
# Exits after a successful fire (re-arm manually for a second window).
set -u
cd "$(dirname "$0")/.."
mkdir -p /tmp/onchip_r5
N=0
while true; do
  N=$((N+1))
  T=$(date -u +%H:%M:%S)
  if timeout 90 python -c "import jax; assert jax.devices()" 2>/dev/null; then
    echo "[$T] probe $N: TUNNEL UP — firing bench suite"
    BENCH_PROBE_BUDGET_S=60 BENCH_EXTRAS_TIMEOUT_S=900 \
      timeout 7200 python bench.py \
      > /tmp/onchip_r5/bench_stdout.$N.json 2> /tmp/onchip_r5/bench_stderr.$N.log
    rc=$?
    echo "[$(date -u +%H:%M:%S)] bench rc=$rc — record:"
    cat /tmp/onchip_r5/bench_stdout.$N.json
    # only a REAL on-chip record ends the hunt; a crash or a CPU-fallback
    # record (tunnel wedged mid-run) re-arms the loop for the next window
    if [ $rc -eq 0 ] && ! grep -q cpu_fallback /tmp/onchip_r5/bench_stdout.$N.json; then
      cp /tmp/onchip_r5/bench_stdout.$N.json /tmp/onchip_r5/bench_stdout.json
      exit 0
    fi
    echo "re-arming (rc=$rc or cpu_fallback)"
  else
    echo "[$T] probe $N: down"
  fi
  sleep 300
done
