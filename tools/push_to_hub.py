"""Push an HF-format export to the HuggingFace Hub.

TPU-native port of the reference's upload tool (ref: tools/push_to_hub.py):
loads a transformers checkpoint directory (e.g. produced by
`tools/convert_hf_checkpoint.py export`), optionally converts dtype, and
uploads model + tokenizer with sharded safetensor serialization.

  python tools/push_to_hub.py /path/to/hf_export \
      --hf_repo_name org/model --auth_token hf_... [--dtype bf16]
"""
from __future__ import annotations

import argparse


DTYPES = {"auto": "auto", "bf16": "bfloat16", "fp16": "float16",
          "fp32": "float32"}


def parse_args():
    p = argparse.ArgumentParser(
        description="Push an HF-format checkpoint to the HuggingFace Hub.")
    p.add_argument("model_name", help="path to HF checkpoint or model name")
    p.add_argument("--dtype", choices=sorted(DTYPES), default="auto")
    p.add_argument("--hf_repo_name", required=True)
    p.add_argument("--auth_token", default=None)
    p.add_argument("--output_folder", default=None,
                   help="also save locally (e.g. after dtype conversion)")
    p.add_argument("--max_shard_size", default="10GB")
    p.add_argument("--unsafe", action="store_true",
                   help="disable safetensor serialization")
    return p.parse_args()


def main():
    import torch
    from transformers import AutoModelForCausalLM, AutoTokenizer

    args = parse_args()
    dtype = DTYPES[args.dtype]
    torch_dtype = dtype if dtype == "auto" else getattr(torch, dtype)
    model = AutoModelForCausalLM.from_pretrained(
        args.model_name, torch_dtype=torch_dtype)
    try:
        tokenizer = AutoTokenizer.from_pretrained(args.model_name)
    except (OSError, ValueError):
        # exports from convert_hf_checkpoint.py carry weights + config only;
        # push the model anyway and say so
        tokenizer = None
        print(f"note: no tokenizer files at {args.model_name}; "
              "pushing weights/config only")

    if args.output_folder:
        model.save_pretrained(args.output_folder,
                              max_shard_size=args.max_shard_size,
                              safe_serialization=not args.unsafe)
        if tokenizer is not None:
            tokenizer.save_pretrained(args.output_folder)

    model.push_to_hub(args.hf_repo_name, token=args.auth_token,
                      max_shard_size=args.max_shard_size,
                      safe_serialization=not args.unsafe)
    if tokenizer is not None:
        tokenizer.push_to_hub(args.hf_repo_name, token=args.auth_token)
    print(f"pushed {args.model_name} to {args.hf_repo_name}")


if __name__ == "__main__":
    main()
