"""CPU-execution shims for the reference Megatron codebase.

The reference hard-imports CUDA-only packages (apex, amp_C, flash_attn)
and calls .cuda()/torch.cuda.* throughout. These shims install
numerically-equivalent torch-CPU stand-ins BEFORE `import megatron`, so
the reference's own model/loader/training code runs on this machine —
the missing half of the cross-implementation gate (VERDICT r4 #3
stretch: run the reference itself on CPU against the same data).

Equivalences used (each checked against the apex source semantics):
- apex.optimizers.FusedAdam(adam_w_mode=True default) == torch.optim
  .AdamW with the same (lr, betas, eps, weight_decay); FusedSGD == SGD.
- amp_C.multi_tensor_l2norm == global l2 over the tensor list;
  multi_tensor_scale == elementwise copy-with-scale.
- flash_attn is stubbed to raise (runs must use --no flash attn paths).
- torch.cuda RNG entry points map to the CPU generator so
  tensor_parallel/random.py's fork/restore machinery still functions.

Import and call install() before any `import megatron`.
"""
from __future__ import annotations

import sys
import types

import torch


def _mk(name):
    m = types.ModuleType(name)
    sys.modules[name] = m
    return m


_INSTALLED = False


def install():
    # sentinel, NOT "apex in sys.modules": a real apex on the machine
    # must not silently skip the torch.cuda patches below
    global _INSTALLED
    if _INSTALLED:
        return
    _INSTALLED = True

    # torch>=2.6 defaults torch.load(weights_only=True), which rejects
    # the argparse.Namespace / enums / numpy rng-state the reference
    # embeds in its checkpoints. Everything loaded under this shim is a
    # locally-produced trusted file, so default the flag off.
    _orig_load = torch.load

    def _load(*a, **k):
        k.setdefault("weights_only", False)
        return _orig_load(*a, **k)

    torch.load = _load

    # --- apex ---------------------------------------------------------
    apex = _mk("apex")
    mta = _mk("apex.multi_tensor_apply")

    class _Applier:
        available = True

        def __call__(self, op, noop_flag, tensor_lists, *args):
            return op(noop_flag, tensor_lists, *args)

    mta.multi_tensor_applier = _Applier()
    apex.multi_tensor_apply = mta

    opt = _mk("apex.optimizers")

    class FusedAdam(torch.optim.AdamW):
        def __init__(self, params, lr=1e-3, bias_correction=True,
                     betas=(0.9, 0.999), eps=1e-8, adam_w_mode=True,
                     weight_decay=0.0, amsgrad=False, **kw):
            assert adam_w_mode, "shim maps FusedAdam -> AdamW"
            super().__init__(params, lr=lr, betas=betas, eps=eps,
                             weight_decay=weight_decay, amsgrad=amsgrad)

    class FusedSGD(torch.optim.SGD):
        def __init__(self, params, lr=1e-3, momentum=0.0, dampening=0,
                     weight_decay=0.0, nesterov=False, **kw):
            super().__init__(params, lr=lr, momentum=momentum,
                             dampening=dampening,
                             weight_decay=weight_decay, nesterov=nesterov)

    opt.FusedAdam = FusedAdam
    opt.FusedSGD = FusedSGD
    apex.optimizers = opt

    # fused_layer_norm tries apex.contrib + fused cuda modules; give it
    # empty shells so its `except ImportError` fallbacks engage
    _mk("apex.contrib")

    # --- amp_C --------------------------------------------------------
    amp_C = _mk("amp_C")

    def multi_tensor_l2norm(noop_flag, tensor_lists, per_tensor=False):
        (tensors,) = tensor_lists
        if not tensors:
            z = torch.zeros(1)
            return z, z
        norm = torch.norm(
            torch.stack([t.detach().float().norm(2) for t in tensors]), 2)
        return norm.reshape(1), None

    def multi_tensor_scale(noop_flag, tensor_lists, scale):
        src, dst = tensor_lists
        for s, d in zip(src, dst):
            d.copy_(s, non_blocking=False)
            d.mul_(scale)

    amp_C.multi_tensor_l2norm = multi_tensor_l2norm
    amp_C.multi_tensor_scale = multi_tensor_scale

    # --- fused_layer_norm_cuda (apex LN extension) --------------------
    # MixedFusedLayerNorm unconditionally calls these two
    # (ref: megatron/model/fused_layer_norm.py:36,56); plain-torch LN
    # math with the same (output, mean, invvar) contract
    fln = _mk("fused_layer_norm_cuda")

    def _ln_stats(input_, shape, eps):
        dims = tuple(range(input_.dim() - len(shape), input_.dim()))
        x = input_.float()
        mean = x.mean(dims, keepdim=True)
        var = x.var(dims, unbiased=False, keepdim=True)
        invvar = torch.rsqrt(var + eps)
        return x, mean, invvar, dims

    def forward_affine(input_, normalized_shape, weight, bias, eps):
        x, mean, invvar, _ = _ln_stats(input_, normalized_shape, eps)
        out = (x - mean) * invvar * weight.float() + bias.float()
        return out.to(input_.dtype), mean, invvar

    def backward_affine(grad_out, mean, invvar, input_, normalized_shape,
                        weight, bias, eps):
        x = input_.float()
        g = grad_out.float()
        dims = tuple(range(input_.dim() - len(normalized_shape),
                           input_.dim()))
        n = 1
        for d in dims:
            n *= input_.shape[d]
        xhat = (x - mean) * invvar
        gw = g * weight.float()
        dx = (invvar / n) * (n * gw - gw.sum(dims, keepdim=True)
                             - xhat * (gw * xhat).sum(dims, keepdim=True))
        outer = tuple(range(input_.dim() - len(normalized_shape)))
        dweight = (g * xhat).sum(outer)
        dbias = g.sum(outer)
        return (dx.to(input_.dtype), dweight.to(weight.dtype),
                dbias.to(bias.dtype))

    fln.forward_affine = forward_affine
    fln.backward_affine = backward_affine

    # --- flash_attn (import-time only; CPU runs keep it disabled) -----
    fa = _mk("flash_attn")

    def _no_flash(*a, **k):
        raise RuntimeError("flash_attn shim: run with use_flash_attn off")

    fa.flash_attn_func = _no_flash
    _mk("flash_attn.flash_attn_interface").flash_attn_func = _no_flash

    # --- torch.cuda on CPU --------------------------------------------
    # moves become no-ops; RNG maps to the CPU generator so the
    # tensor-parallel rng tracker forks/restores real state
    torch.Tensor.cuda = lambda self, *a, **k: self
    torch.nn.Module.cuda = lambda self, *a, **k: self
    # megatron asserts tensor.type() == 'torch.cuda.FloatTensor'
    # (clip_grads.py:50); report the cuda spelling for no-arg calls
    _orig_type = torch.Tensor.type

    def _type(self, dtype=None, **kw):
        if dtype is None:
            return _orig_type(self).replace("torch.", "torch.cuda.", 1)
        return _orig_type(self, dtype, **kw)

    torch.Tensor.type = _type
    tc = torch.cuda
    # True: initialize_megatron asserts CUDA; every actual device
    # operation is a no-op'd move or a CPU-RNG mapping below
    tc.is_available = lambda: True
    # "cpu" (not 0): megatron passes current_device() straight into
    # device= kwargs, and device 0 would resolve to the absent cuda:0
    tc.current_device = lambda: "cpu"
    tc.set_device = lambda *a, **k: None
    tc.device_count = lambda: 1
    tc.synchronize = lambda *a, **k: None
    tc.empty_cache = lambda: None
    tc.get_rng_state = lambda *a, **k: torch.get_rng_state()
    tc.set_rng_state = lambda s, *a, **k: torch.set_rng_state(s)
    tc.manual_seed = lambda s: None
    tc.memory_allocated = lambda *a, **k: 0
    tc.max_memory_allocated = lambda *a, **k: 0
    tc.reset_peak_memory_stats = lambda *a, **k: None
    tc.memory_reserved = lambda *a, **k: 0
    tc.max_memory_reserved = lambda *a, **k: 0
    # real torch.Tensor SUBCLASSES (not lambdas): megatron builds
    # isinstance tuples from these (model/module.py _FLOAT_TYPES), and
    # isinstance() needs types — a lambda would raise TypeError there.
    # Calling them constructs CPU tensors of the right dtype.
    def _cpu_tensor_type(name, dtype):
        def _new(cls, *a, **k):
            if a and all(isinstance(x, int) for x in a):
                return torch.zeros(a, dtype=dtype)
            return torch.tensor(a[0] if a else [], dtype=dtype)
        return type(name, (torch.Tensor,), {"__new__": _new})

    tc.DoubleTensor = _cpu_tensor_type("DoubleTensor", torch.float64)
    tc.FloatTensor = _cpu_tensor_type("FloatTensor", torch.float32)
    tc.HalfTensor = _cpu_tensor_type("HalfTensor", torch.float16)
    tc.BFloat16Tensor = _cpu_tensor_type("BFloat16Tensor", torch.bfloat16)
    tc.LongTensor = _cpu_tensor_type("LongTensor", torch.int64)
    tc.IntTensor = _cpu_tensor_type("IntTensor", torch.int32)
