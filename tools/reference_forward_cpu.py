"""Run the REFERENCE's GPT/Llama model on CPU over one batch and dump
logits — the executable half of the cross-implementation gate.

Loads a megatron-layout checkpoint (e.g. one written by OUR
convert/megatron.save_megatron_checkpoint), builds the reference's own
LlamaModel via its own initialize/arguments/checkpointing machinery
(under tools/reference_cpu_shim), and writes fp32 logits for the given
tokens. The companion test (tests/test_reference_cpu.py) compares them
against megatron_tpu's forward on the same weights — OUR exporter +
THEIR loader + THEIR model vs OUR model, end to end, no network.

  python tools/reference_forward_cpu.py --ref_path /root/reference \
      --load <ckpt dir> --tokens tokens.npy --out logits.npz \
      --num_layers 4 --hidden_size 64 --num_attention_heads 4 \
      --num_kv 2 --ffn 176 --vocab 128 --seq 64
"""
from __future__ import annotations

import argparse
import os
import sys


def main(argv=None):
    p = argparse.ArgumentParser("reference_forward_cpu")
    p.add_argument("--ref_path", default="/root/reference")
    p.add_argument("--load", required=True)
    p.add_argument("--tokens", required=True)  # .npy int32 [b, s]
    p.add_argument("--out", required=True)
    p.add_argument("--num_layers", type=int, required=True)
    p.add_argument("--hidden_size", type=int, required=True)
    p.add_argument("--num_attention_heads", type=int, required=True)
    p.add_argument("--num_kv", type=int, required=True)
    p.add_argument("--ffn", type=int, required=True)
    p.add_argument("--vocab", type=int, required=True)
    p.add_argument("--seq", type=int, required=True)
    # llama: rotary + rmsnorm + swiglu + untied head, no biases
    # gpt: learned absolute positions + layernorm + erf-gelu + biases +
    #      tied embeddings (the reference's GPTModel defaults)
    p.add_argument("--family", default="llama", choices=["llama", "gpt"])
    # --train N: instead of one forward, run N full training steps
    # (their model fwd/bwd + their FP32Optimizer: clip -> adamw) on
    # batches from --tokens shaped [N, b, s+1]; dump per-step losses.
    p.add_argument("--train", type=int, default=0)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--weight_decay", type=float, default=0.01)
    p.add_argument("--clip_grad", type=float, default=1.0)
    # after --train: have the REFERENCE'S OWN save_checkpoint write its
    # mp_rank layout here (the real writer — importer tests use it)
    p.add_argument("--save_after", type=str, default=None)
    args = p.parse_args(argv)
    if args.save_after and not args.train:
        p.error("--save_after requires --train N (only the training "
                "path saves)")

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import reference_cpu_shim
    reference_cpu_shim.install()
    sys.path.insert(0, args.ref_path)

    import numpy as np
    import torch

    # single-process gloo "distributed" run
    os.environ.setdefault("MASTER_ADDR", "127.0.0.1")
    os.environ.setdefault("MASTER_PORT", "29511")
    os.environ["WORLD_SIZE"] = "1"
    os.environ["RANK"] = "0"
    os.environ["LOCAL_RANK"] = "0"

    sys.argv = [
        "reference_forward_cpu",
        "--num_layers", str(args.num_layers),
        "--hidden_size", str(args.hidden_size),
        "--num_attention_heads", str(args.num_attention_heads),
        "--num_attention_heads_kv", str(args.num_kv),
        "--ffn_hidden_size", str(args.ffn),
        "--seq_length", str(args.seq),
        "--max_position_embeddings", str(args.seq),
        "--micro_batch_size", "2",
        "--global_batch_size", "2",
        "--load", args.load,
        "--no_load_optim", "--no_load_rng", "--finetune",
        "--distributed_backend", "gloo",
        # NOT --use_cpu_initialization: the reference's cpu-init path has
        # a latent bug (language_model.py:452 calls
        # _initialize_affine_weight_cpu without init_method); the normal
        # path works because the shim maps its cuda RNG onto the CPU
        # generator
        "--no_masked_softmax_fusion",
        "--no_bias_gelu_fusion", "--no_bias_dropout_fusion",
        "--layernorm_epsilon", "1e-5",
        "--hidden_dropout", "0.0", "--attention_dropout", "0.0",
        "--make_vocab_size_divisible_by", "1",
        "--no_gradient_accumulation_fusion",
        # torch DDP impl: params_have_main_grad=False, so the manual
        # training loop below works on a bare (unwrapped) module
        "--DDP_impl", "torch",
        "--optimizer", "adam",
        "--lr", str(args.lr),
        "--lr_decay_style", "constant",
        "--weight_decay", str(args.weight_decay),
        "--clip_grad", str(args.clip_grad),
        "--adam_beta1", "0.9", "--adam_beta2", "0.999",
        "--adam_eps", "1e-8",
    ] + {
        "llama": ["--position_embedding_type", "rotary", "--use_rms_norm",
                  "--glu_activation", "swiglu", "--no_tie_embed_logits"],
        "gpt": ["--position_embedding_type", "absolute", "--use_bias"],
    }[args.family]

    from megatron import get_args, initialize
    from megatron.model import GPTModel
    from megatron.model.llama_model import LlamaModel
    from megatron.model.enums import ModelType
    from megatron import checkpointing
    from megatron.utils import get_ltor_masks_and_position_ids
    # (enum-laden checkpoint loading works because the shim defaults
    # torch.load to weights_only=False — no allowlist needed here)

    # no vocab_file + a non-listed tokenizer type -> set_global_variables
    # skips tokenizer construction entirely; padded_vocab_size (normally
    # tokenizer-derived) is injected below before the model builds
    initialize.initialize_megatron(extra_args_provider=None,
                                   args_defaults={})
    margs = get_args()
    margs.padded_vocab_size = args.vocab
    margs.model_type = ModelType.encoder_or_decoder

    torch.manual_seed(margs.seed)
    cls = LlamaModel if args.family == "llama" else GPTModel
    model = cls(num_tokentypes=0, parallel_output=False,
                pre_process=True, post_process=True,
                model_type=ModelType.encoder_or_decoder)
    model = model.float().eval()

    it = checkpointing.load_checkpoint([model], None, None)
    print(f"loaded checkpoint at iteration {it}")

    if args.train:
        return _train(args, margs, model)

    tokens = torch.tensor(np.load(args.tokens).astype(np.int64))
    attn_mask, _, pos = get_ltor_masks_and_position_ids(
        tokens, margs.padded_vocab_size - 1, False, False, False)
    with torch.no_grad():
        logits = model(tokens, pos, attn_mask).float().numpy()
    np.savez_compressed(args.out, logits=logits)
    print(f"wrote {args.out} logits {logits.shape}")
    return 0


def _train(args, margs, model):
    """N steps of the reference's own training semantics: model fwd/bwd,
    FP32Optimizer (l2 clip -> FusedAdam==AdamW via the shim), constant
    lr — per-step masked-mean losses to --out."""
    import numpy as np
    import torch

    from megatron import get_timers
    from megatron.optimizer import get_megatron_optimizer
    from megatron.utils import get_ltor_masks_and_position_ids

    blocks = np.load(args.tokens).astype(np.int64)  # [N, b, s+1]
    assert blocks.ndim == 3 and blocks.shape[0] >= args.train
    optimizer = get_megatron_optimizer([model])
    # get_param_groups tags no-wd groups (biases, 1-D params) with
    # wd_mult=0.0 but the per-group weight_decay is normally applied by
    # OptimizerParamScheduler (optimizer_param_scheduler.py:127); this
    # loop has no scheduler, so apply the multiplier here or AdamW would
    # decay norm scales the real reference exempts
    for g in optimizer.optimizer.param_groups:
        g["weight_decay"] = margs.weight_decay * g.get("wd_mult", 1.0)
    timers = get_timers()
    model.train()
    losses, grad_norms = [], []
    for i in range(args.train):
        blk = torch.tensor(blocks[i])
        tokens, labels = blk[:, :-1].contiguous(), blk[:, 1:].contiguous()
        attn_mask, _, pos = get_ltor_masks_and_position_ids(
            tokens, margs.padded_vocab_size - 1, False, False, False)
        optimizer.zero_grad()
        per_tok = model(tokens, pos, attn_mask, labels=labels)
        loss = per_tok.float().mean()
        loss.backward()
        ok, gnorm, _ = optimizer.step(margs, timers)
        assert ok
        losses.append(float(loss))
        grad_norms.append(float(gnorm) if gnorm is not None else 0.0)
        print(f"step {i}: loss {losses[-1]:.6f} grad_norm "
              f"{grad_norms[-1]:.4f}", flush=True)
    if args.save_after:
        from megatron import checkpointing
        margs.save = args.save_after
        checkpointing.save_checkpoint(args.train, [model], None, None)
        print(f"reference save_checkpoint wrote {args.save_after}")
    np.savez_compressed(args.out, losses=np.asarray(losses),
                        grad_norms=np.asarray(grad_norms))
    print(f"wrote {args.out} ({args.train} steps)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
