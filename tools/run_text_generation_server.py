"""Launch the REST text-generation server from a checkpoint.

TPU-native port of /root/reference/tools/run_text_generation_server.py:60-84.

  python tools/run_text_generation_server.py --load ckpts/llama7b \
      --tokenizer_type SentencePieceTokenizer --tokenizer_model tok.model \
      --port 5000
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform
ensure_env_platform()


def main(argv=None):
    import jax

    from megatron_tpu.data import build_tokenizer
    from megatron_tpu.inference.generation import Generator
    from megatron_tpu.inference.server import MegatronServer
    from megatron_tpu.models import language_model as lm
    from megatron_tpu.training import checkpointing as ckpt
    from megatron_tpu.training.train_step import TrainState

    p = argparse.ArgumentParser()
    p.add_argument("--load", default=None,
                   help="checkpoint root to serve (required unless "
                        "--fleet: a front tier holds no weights)")
    p.add_argument("--tokenizer_type", default="SentencePieceTokenizer")
    p.add_argument("--tokenizer_model", default=None)
    p.add_argument("--vocab_file", default=None)
    p.add_argument("--merge_file", default=None)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=5000)
    p.add_argument("--int8_weights", action="store_true",
                   help="serve with int8-resident transformer weights "
                        "(ops/quantized.quantize_weights): halves the "
                        "decode weight stream at ~0.5%% logit error. "
                        "MoE expert banks are NOT quantized (the router "
                        "dict is skipped), so for Mixtral-class models "
                        "(~95%% of params in experts) the reduction is "
                        "small — use --int8_kv there instead")
    p.add_argument("--int8_kv", action="store_true",
                   help="serve with an int8 KV cache: halves the cache "
                        "stream and residency — at 7B/32k the bf16 "
                        "cache alone outgrows a v5e")
    # continuous-batching engine knobs (megatron_tpu/serving)
    p.add_argument("--num_slots", type=int, default=None,
                   help="batch slots in the persistent decode grid = "
                        "max concurrently-decoding requests. Default: "
                        "up to 8, clamped to what free device memory "
                        "fits AFTER the weights (the slot-grid pool is "
                        "allocated eagerly — 8 full-context Llama-7B "
                        "bf16 slots alone are ~17 GB)")
    p.add_argument("--max_queue", type=int, default=64,
                   help="bounded admission queue; overflow returns 429")
    p.add_argument("--serving_max_len", type=int, default=None,
                   help="per-slot KV region length (prompt+generated); "
                        "defaults to max_position_embeddings")
    p.add_argument("--request_deadline_s", type=float, default=None,
                   help="per-request wall-clock deadline: queued or "
                        "running requests past it are evicted and "
                        "answer 504 (None = no deadline)")
    p.add_argument("--serial", action="store_true",
                   help="serve with the reference's serial one-lock "
                        "path instead of the continuous-batching engine")
    p.add_argument("--adapter_slots", type=int, default=0,
                   help="multi-tenant LoRA serving: device-resident "
                        "adapters servable concurrently (0 disables; "
                        "docs/serving.md 'Multi-tenant LoRA serving')")
    p.add_argument("--adapter_rank", type=int, default=8,
                   help="LoRA rank the adapter bank allocates for")
    p.add_argument("--adapter_host_bytes", type=int, default=0,
                   help="host-RAM overflow budget for evicted adapters")
    p.add_argument("--adapter_dir", type=str, default=None,
                   help="directory of adapter .npz exports (finetune "
                        "--lora_rank) registered at start; adapter_id "
                        "= file stem")
    p.add_argument("--serving_tp", type=int, default=1,
                   help="tensor-parallel width of the serving mesh "
                        "(weights + KV arena shard over 'tp' on the "
                        "head axes; 1 = single-device engine — "
                        "docs/serving.md 'Sharded & disaggregated "
                        "serving')")
    p.add_argument("--kv_block_size", type=int, default=None,
                   help="block-granular KV pool (required by "
                        "--disaggregate_prefill; docs/serving.md)")
    p.add_argument("--disaggregate_prefill", action="store_true",
                   help="prefill and decode on separate serving_tp-"
                        "wide chip groups; the handoff moves only the "
                        "sequence's live KV blocks (needs "
                        "--kv_block_size)")
    p.add_argument("--watch_checkpoints", action="store_true",
                   help="live-weight serving: poll --load's tracker "
                        "and hot-swap (or rolling-upgrade the replica "
                        "fleet to) every newly published checkpoint — "
                        "trainers drive the server with zero operator "
                        "action (docs/serving.md 'Live weights & "
                        "rolling upgrade')")
    p.add_argument("--watch_interval_s", type=float, default=5.0,
                   help="tracker poll cadence for --watch_checkpoints")
    p.add_argument("--swap_timeout_s", type=float, default=120.0,
                   help="live-weight swap barrier budget: how long a "
                        "hot swap waits for in-flight work before it "
                        "cancels (typed refusal, engine keeps serving)")
    # networked front door (docs/serving.md "Front door": process-
    # boundary deployment; serving/remote.py)
    p.add_argument("--replica_mode", action="store_true",
                   help="run this server as one fleet replica process: "
                        "accepts the pre-tokenized prompt_tokens wire "
                        "format plus the /admin /invariants /affinity "
                        "control-plane routes a remote front tier "
                        "(--fleet) drives")
    p.add_argument("--fleet", type=str, default=None,
                   help="run as a thin FRONT TIER over remote replica "
                        "processes at these host:port addresses "
                        "(comma-separated): the prefix-affinity router "
                        "with health polling, typed transport faults, "
                        "token-exact failover, and rolling upgrades "
                        "over TCP — no weights load in this process")
    p.add_argument("--remote_connect_timeout_s", type=float, default=2.0,
                   help="fleet: per-call TCP connect (and health-probe "
                        "read) budget to a replica")
    p.add_argument("--remote_read_timeout_s", type=float, default=30.0,
                   help="fleet: per-call read budget on replica "
                        "responses and SSE inter-frame gaps")
    p.add_argument("--remote_max_retries", type=int, default=2,
                   help="fleet: bounded transport-level retries per "
                        "remote call (exponential backoff + jitter, "
                        "Retry-After honored); whole-request failover "
                        "to a survivor is governed by "
                        "--router_max_retries on top")
    p.add_argument("--remote_digest_interval_s", type=float, default=2.0,
                   help="fleet: refresh cadence of each replica's "
                        "prefix-affinity digest (GET /affinity) — "
                        "staleness only skews routing hints, never "
                        "tokens")
    args = p.parse_args(argv)
    if args.fleet and args.load:
        p.error("--fleet is a thin front tier over remote replicas; it "
                "loads no weights (drop --load)")
    if not args.fleet and not args.load:
        p.error("--load is required (or --fleet for a front tier)")
    if args.fleet and (args.serial or args.replica_mode):
        p.error("--fleet excludes --serial and --replica_mode: the "
                "front tier routes, it does not serve an engine")
    if args.replica_mode and args.serial:
        p.error("--replica_mode requires the serving engine (drop "
                "--serial)")
    if args.fleet:
        # the front tier needs only a tokenizer (text prompts in,
        # pre-tokenized prompt_tokens over the wire) and the router —
        # build neither model nor engine here
        from megatron_tpu.config import ServingConfig
        from megatron_tpu.data import build_tokenizer as _bt
        tokenizer = _bt(args.tokenizer_type, vocab_file=args.vocab_file,
                        merge_file=args.merge_file,
                        tokenizer_model=args.tokenizer_model)
        serving = ServingConfig(
            fleet=args.fleet,
            max_queue=args.max_queue,
            request_deadline_s=args.request_deadline_s,
            remote_connect_timeout_s=args.remote_connect_timeout_s,
            remote_read_timeout_s=args.remote_read_timeout_s,
            remote_max_retries=args.remote_max_retries,
            remote_digest_interval_s=args.remote_digest_interval_s,
            watch_checkpoints=(args.load if args.watch_checkpoints
                               else None),
            watch_interval_s=args.watch_interval_s).validate(None)
        server = MegatronServer(None, tokenizer, serving=serving)
        server.run(args.host, args.port)
        return
    if args.watch_checkpoints and args.serial:
        p.error("--watch_checkpoints requires the serving engine "
                "(drop --serial): the serial path has nothing to "
                "hot-swap")
    if args.watch_checkpoints and args.int8_weights:
        # the engine's swap stages the published FP params tree against
        # gen.params — an int8-resident engine holds the quantized tree
        # (different structure), so every publish would be refused and
        # weight_swap_failures would climb forever. Fail the flag combo
        # loudly instead of shipping a watcher that can never apply.
        p.error("--watch_checkpoints is unsupported with "
                "--int8_weights: hot swap stages the published fp "
                "checkpoint against the engine's params tree, and the "
                "int8-resident tree has a different structure — serve "
                "fp weights (--int8_kv stays available) or drop the "
                "watcher")
    if args.adapter_dir and (args.serial or args.adapter_slots <= 0):
        # fail loudly at the flag boundary: the serial path threads no
        # adapter bank, and without --adapter_slots there is no bank
        # to register into (server.engine would be None / bankless and
        # the registration loop below would crash unexplanatorily)
        p.error("--adapter_dir requires --adapter_slots > 0 and the "
                "serving engine (drop --serial)")

    cfg = ckpt.load_config_from_checkpoint(args.load)
    assert cfg is not None, f"no checkpoint under {args.load}"
    mcfg = cfg.model
    example = TrainState(
        params=jax.eval_shape(lambda: lm.model_init(jax.random.PRNGKey(0),
                                                    mcfg)),
        opt_state=None, iteration=0)
    tokenizer = build_tokenizer(
        args.tokenizer_type, vocab_file=args.vocab_file,
        merge_file=args.merge_file, tokenizer_model=args.tokenizer_model)
    import jax.numpy as jnp

    staged_version = None
    if args.serial or args.int8_weights:
        # serial fallback needs device params anyway; the int8 path
        # quantizes on device and drops the fp originals below
        state, _, _ = ckpt.load_checkpoint(args.load, example,
                                           no_load_optim=True)
        assert state is not None, \
            f"failed to load checkpoint from {args.load}"
        params = state.params
        if args.int8_weights:
            from megatron_tpu.ops.quantized import quantize_weights
            params = quantize_weights(params)
            # drop the fp originals BEFORE serving: `state` would
            # otherwise pin them in device memory for the server's
            # whole lifetime, growing residency ~1.25x instead of
            # shrinking it ~4x
            state = None
    else:
        # HOST-FIRST staging (docs/serving.md "Live weights & rolling
        # upgrade"): params stay NumPy and the engine's placement
        # (sharded per group under --serving_tp/--disaggregate_prefill)
        # is the ONLY device residency — device 0 never pays
        # full-model + shard residency — and the served weight_version
        # (iteration + manifest digest) is known from startup. This is
        # the same mechanism hot swap uses.
        from megatron_tpu.serving.weights import stage_latest
        from megatron_tpu.utils.logging import print_rank_0
        staged = stage_latest(args.load, example.params)
        params = staged.params
        staged_version = staged.version
        print_rank_0(f"serving: staged weights host-side "
                     f"(version {staged_version.label}); device "
                     "residency = the engine's placement only")
    gen = Generator(params, mcfg, eos_id=tokenizer.eod,
                    kv_cache_dtype=jnp.int8 if args.int8_kv
                    else jnp.bfloat16)
    from megatron_tpu.config import ServingConfig
    num_slots = args.num_slots
    if num_slots is None and not args.serial:
        # size the eager slot-grid pool to the memory the weights left
        # free (a fixed 8-slot default OOMs 7B-class serving on a v5e)
        from megatron_tpu.serving.kv_pool import fit_num_slots
        from megatron_tpu.utils.logging import print_rank_0
        num_slots = fit_num_slots(
            mcfg, args.serving_max_len or mcfg.max_position_embeddings,
            dtype=jnp.int8 if args.int8_kv else jnp.bfloat16)
        print_rank_0(f"serving: auto-sized num_slots={num_slots} "
                     "(override with --num_slots)")
    if num_slots is None:  # serial fallback: engine never built
        num_slots = 8
    serving = ServingConfig(num_slots=num_slots,
                            max_queue=args.max_queue,
                            max_len=args.serving_max_len,
                            serial_fallback=args.serial,
                            request_deadline_s=args.request_deadline_s,
                            adapter_slots=args.adapter_slots,
                            adapter_rank=args.adapter_rank,
                            adapter_host_bytes=args.adapter_host_bytes,
                            serving_tp=args.serving_tp,
                            kv_block_size=args.kv_block_size,
                            disaggregate_prefill=args.disaggregate_prefill,
                            swap_timeout_s=args.swap_timeout_s,
                            replica_mode=args.replica_mode,
                            watch_checkpoints=(args.load
                                               if args.watch_checkpoints
                                               else None),
                            watch_interval_s=args.watch_interval_s
                            ).validate(mcfg)
    server = MegatronServer(gen, tokenizer, serving=serving,
                            weight_version=staged_version)
    if args.adapter_dir:
        # pre-register every exported adapter: adapter_id = file stem,
        # validated eagerly (a corrupt export fails the server start,
        # not some later request's admission)
        import glob
        from megatron_tpu.utils.logging import print_rank_0
        for path in sorted(glob.glob(os.path.join(args.adapter_dir,
                                                  "*.npz"))):
            aid = os.path.splitext(os.path.basename(path))[0]
            server.engine.register_adapter(aid, path=path)
            print_rank_0(f"serving: registered adapter {aid!r} "
                         f"from {path}")
    server.run(args.host, args.port)


if __name__ == "__main__":
    main()
