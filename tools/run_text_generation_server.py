"""Launch the REST text-generation server from a checkpoint.

TPU-native port of /root/reference/tools/run_text_generation_server.py:60-84.

  python tools/run_text_generation_server.py --load ckpts/llama7b \
      --tokenizer_type SentencePieceTokenizer --tokenizer_model tok.model \
      --port 5000
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform
ensure_env_platform()


def main(argv=None):
    import jax

    from megatron_tpu.data import build_tokenizer
    from megatron_tpu.inference.generation import Generator
    from megatron_tpu.inference.server import MegatronServer
    from megatron_tpu.models import language_model as lm
    from megatron_tpu.training import checkpointing as ckpt
    from megatron_tpu.training.train_step import TrainState

    p = argparse.ArgumentParser()
    p.add_argument("--load", required=True)
    p.add_argument("--tokenizer_type", default="SentencePieceTokenizer")
    p.add_argument("--tokenizer_model", default=None)
    p.add_argument("--vocab_file", default=None)
    p.add_argument("--merge_file", default=None)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=5000)
    p.add_argument("--int8_weights", action="store_true",
                   help="serve with int8-resident transformer weights "
                        "(ops/quantized.quantize_weights): halves the "
                        "decode weight stream at ~0.5%% logit error. "
                        "MoE expert banks are NOT quantized (the router "
                        "dict is skipped), so for Mixtral-class models "
                        "(~95%% of params in experts) the reduction is "
                        "small — use --int8_kv there instead")
    p.add_argument("--int8_kv", action="store_true",
                   help="serve with an int8 KV cache: halves the cache "
                        "stream and residency — at 7B/32k the bf16 "
                        "cache alone outgrows a v5e")
    args = p.parse_args(argv)

    cfg = ckpt.load_config_from_checkpoint(args.load)
    assert cfg is not None, f"no checkpoint under {args.load}"
    mcfg = cfg.model
    example = TrainState(
        params=jax.eval_shape(lambda: lm.model_init(jax.random.PRNGKey(0),
                                                    mcfg)),
        opt_state=None, iteration=0)
    state, _, _ = ckpt.load_checkpoint(args.load, example, no_load_optim=True)
    assert state is not None, f"failed to load checkpoint from {args.load}"
    tokenizer = build_tokenizer(
        args.tokenizer_type, vocab_file=args.vocab_file,
        merge_file=args.merge_file, tokenizer_model=args.tokenizer_model)
    import jax.numpy as jnp

    params = state.params
    if args.int8_weights:
        from megatron_tpu.ops.quantized import quantize_weights
        params = quantize_weights(params)
        # drop the fp originals BEFORE serving: `state` would otherwise
        # pin them in device memory for the server's whole lifetime,
        # growing residency ~1.25x instead of shrinking it ~4x
        state = None
    gen = Generator(params, mcfg, eos_id=tokenizer.eod,
                    kv_cache_dtype=jnp.int8 if args.int8_kv
                    else jnp.bfloat16)
    MegatronServer(gen, tokenizer).run(args.host, args.port)


if __name__ == "__main__":
    main()
