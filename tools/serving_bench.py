"""Concurrent-load micro-bench for the continuous-batching engine.

The training benches measure the MXU-bound path and bench_decode.py the
single-stream serving path; this measures the ENGINE under concurrent
load — the numbers a capacity plan needs: offered load vs sustained
throughput, TTFT percentiles, slot occupancy. Emits ONE BENCH-style
JSON record on stdout (and to --out), like bench.py.

Three modes:
- in-process (default): builds a model (random params at the given
  shape), drives `ServingEngine` directly at `--rps` offered load
  (0 = submit everything at once);
- `--url host:port`: fires the same load as concurrent HTTP PUTs at a
  RUNNING server (examples/serve.sh LOAD=1 wires this up). TTFT is not
  observable over the non-streaming HTTP contract, so the record
  carries whole-request latency percentiles instead;
- `--overload`: in-process engine driven past slot capacity with
  per-request deadlines and early shedding on
  (docs/serving.md "Overload & failure behavior") — reports shed rate,
  goodput (completions within deadline, per second), and p99 queue
  delay: the numbers an admission-control regression moves first.

  python tools/serving_bench.py [--requests N] [--slots N] [--rps R]
                                [--prompt N] [--new N] [--out FILE]
                                [--overload] [--deadline S]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_tpu.utils.platform import ensure_env_platform


def _percentile(vals, q):
    # same nearest-rank convention as the server's /metrics snapshot
    from megatron_tpu.serving.metrics import _percentile as p
    return p(sorted(vals), q)


def _build_workload(args, eos_id: int):
    """Shared model/generator/prompt setup for the in-process arms —
    one definition, so the engine and overload arms always measure
    the same workload shape."""
    import jax
    import numpy as np

    from megatron_tpu.config import ModelConfig
    from megatron_tpu.inference.generation import Generator
    from megatron_tpu.models import language_model as lm

    cfg = ModelConfig(
        num_layers=args.layers, hidden_size=args.hidden,
        num_attention_heads=args.heads,
        num_kv_heads=max(args.heads // 2, 1), vocab_size=args.vocab,
        seq_length=args.seq, max_position_embeddings=args.seq,
        make_vocab_size_divisible_by=64,
        compute_dtype="bfloat16").derived()
    params = lm.model_init(jax.random.PRNGKey(0), cfg)
    gen = Generator(params, cfg, eos_id=eos_id, pad_id=0)
    rs = np.random.RandomState(0)
    prompts = [rs.randint(1, cfg.vocab_size,
                          size=rs.randint(max(args.prompt // 2, 1),
                                          args.prompt + 1)).tolist()
               for _ in range(args.requests)]
    return gen, prompts


def _pace(args, t0: float, i: int):
    """Offered-load pacing shared by the in-process arms."""
    if args.rps > 0:
        target = t0 + i / args.rps
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)


def _bench_engine(args) -> dict:
    from megatron_tpu.config import ServingConfig
    from megatron_tpu.serving import SamplingOptions, ServingEngine

    gen, prompts = _build_workload(args, eos_id=0)
    serving = ServingConfig(num_slots=args.slots,
                            max_queue=max(args.requests, 64))

    with ServingEngine(gen, serving) as eng:
        # warmup: compile prefill buckets + the one decode step
        eng.generate(prompts[0], 2,
                     SamplingOptions(temperature=1.0), seed=0)
        t0 = time.monotonic()
        reqs = []
        for i, p in enumerate(prompts):
            _pace(args, t0, i)
            reqs.append(eng.submit(p, args.new,
                                   SamplingOptions(temperature=1.0),
                                   seed=i))
        gen_tokens = 0
        for r in reqs:
            toks, _ = r.result(timeout=600)
            gen_tokens += len(toks) - len(r.prompt)
        wall = time.monotonic() - t0
        ttfts = [r.ttft for r in reqs if r.ttft is not None]
        snap = eng.metrics.snapshot()
    return {
        "bench": "serving", "mode": "engine",
        "slots": args.slots, "requests": args.requests,
        "offered_rps": args.rps,
        "prompt_len_max": args.prompt, "new_tokens": args.new,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(gen_tokens / max(wall, 1e-9), 2),
        "ttft_p50_ms": round(_percentile(ttfts, 0.50) * 1e3, 1),
        "ttft_p95_ms": round(_percentile(ttfts, 0.95) * 1e3, 1),
        "slot_occupancy": round(snap["slot_occupancy"], 3),
        "decode_steps": int(snap["decode_steps"]),
    }


def _bench_overload(args) -> dict:
    """Offered load > slot capacity: every request carries a deadline,
    the engine sheds what cannot make it (`shed_on_overload`) and
    504s what expires anyway. Goodput counts completions WITHIN the
    deadline — the engine enforces it, so every completion qualifies."""
    from megatron_tpu.config import ServingConfig
    from megatron_tpu.serving import (DeadlineExceededError,
                                      QueueFullError, SamplingOptions,
                                      ServingEngine)

    # eos_id=-1: deterministic request lifetimes, so "offered load vs
    # capacity" is controlled by --requests/--new, not sampling luck
    gen, prompts = _build_workload(args, eos_id=-1)
    serving = ServingConfig(num_slots=args.slots,
                            max_queue=max(args.requests, 64),
                            shed_on_overload=True,
                            request_deadline_s=args.deadline)

    with ServingEngine(gen, serving) as eng:
        # warmup compiles AND seeds the shed estimator's service-time
        # EWMA (it never sheds before the first observed completion);
        # a per-request deadline override keeps the compile-heavy
        # warmup from 504ing against the measured arm's tight default
        eng.submit(prompts[0], args.new,
                   SamplingOptions(temperature=1.0), seed=0,
                   deadline_s=600.0).result(timeout=600)
        t0 = time.monotonic()
        reqs, shed = [], 0
        for i, p in enumerate(prompts):
            _pace(args, t0, i)
            try:
                reqs.append(eng.submit(p, args.new,
                                       SamplingOptions(temperature=1.0),
                                       seed=i))
            except QueueFullError:  # shed (or bounded-queue overflow)
                shed += 1
        good, expired = 0, 0
        for r in reqs:
            try:
                r.result(timeout=600)
                good += 1
            except DeadlineExceededError:
                expired += 1
        wall = time.monotonic() - t0
        snap = eng.metrics.snapshot()
    return {
        "bench": "serving", "mode": "overload",
        "slots": args.slots, "requests": args.requests,
        "offered_rps": args.rps, "deadline_s": args.deadline,
        "prompt_len_max": args.prompt, "new_tokens": args.new,
        "wall_s": round(wall, 3),
        "shed": shed, "expired_504": expired,
        "shed_rate": round(shed / max(args.requests, 1), 3),
        "goodput_rps": round(good / max(wall, 1e-9), 2),
        "goodput_frac": round(good / max(args.requests, 1), 3),
        "queue_wait_p99_ms": round(snap["queue_wait_p99_ms"], 1),
        "queue_wait_p50_ms": round(snap["queue_wait_p50_ms"], 1),
    }


def _bench_url(args) -> dict:
    import urllib.request

    lat, lock = [], threading.Lock()
    gen_tokens = [0]
    rejected = [0]  # 429s — real backpressure, reported, not hidden
    failed = [0]    # anything else (4xx/5xx/transport)
    prompt_text = "the quick brown fox " * max(args.prompt // 8, 1)

    def put(payload):
        req = urllib.request.Request(
            f"http://{args.url}/api", data=json.dumps(payload).encode(),
            method="PUT", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=600) as resp:
            return json.loads(resp.read())

    # segments come back as prompt + generated; learn the PROMPT's
    # tokenized length once (tokens_to_generate=0 echoes it) so
    # tokens_per_s counts GENERATED tokens only, comparable with the
    # in-process engine mode
    plen = len(put({"prompts": [prompt_text],
                    "tokens_to_generate": 0})["segments"][0])

    def one(i):
        import urllib.error
        t = time.monotonic()
        try:
            out = put({"prompts": [prompt_text],
                       "tokens_to_generate": args.new,
                       "temperature": 1.0, "random_seed": i})
        except urllib.error.HTTPError as e:
            with lock:
                (rejected if e.code == 429 else failed)[0] += 1
            return
        except Exception:
            with lock:
                failed[0] += 1
            return
        dt = time.monotonic() - t
        with lock:
            lat.append(dt)
            gen_tokens[0] += sum(max(len(s) - plen, 0)
                                 for s in out.get("segments", []))

    t0 = time.monotonic()
    threads = []
    for i in range(args.requests):
        if args.rps > 0:
            target = t0 + i / args.rps
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
        th = threading.Thread(target=one, args=(i,), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=600)
    wall = time.monotonic() - t0
    return {
        "bench": "serving", "mode": "http", "url": args.url,
        "requests": args.requests, "offered_rps": args.rps,
        "completed": len(lat), "rejected_429": rejected[0],
        "failed": failed[0],
        "wall_s": round(wall, 3),
        "tokens_per_s": round(gen_tokens[0] / max(wall, 1e-9), 2),
        "latency_p50_ms": round(_percentile(lat, 0.50) * 1e3, 1),
        "latency_p95_ms": round(_percentile(lat, 0.95) * 1e3, 1),
    }


def main(argv=None):
    ensure_env_platform()
    p = argparse.ArgumentParser("serving_bench", description=__doc__)
    p.add_argument("--out", default="/tmp/serving_bench.log")
    p.add_argument("--url", default=None,
                   help="host:port of a RUNNING server; omit for the "
                        "in-process engine bench")
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--rps", type=float, default=0.0,
                   help="offered load, requests/s (0 = all at once)")
    p.add_argument("--prompt", type=int, default=64,
                   help="max prompt length (engine mode draws uniform "
                        "lengths in [prompt/2, prompt])")
    p.add_argument("--new", type=int, default=32)
    p.add_argument("--overload", action="store_true",
                   help="overload arm: offered load > slot capacity "
                        "with deadlines + early shedding; reports shed "
                        "rate, goodput, p99 queue delay")
    p.add_argument("--deadline", type=float, default=2.0,
                   help="per-request deadline for the overload arm (s)")
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("--seq", type=int, default=512)
    args = p.parse_args(argv)

    if args.url:
        record = _bench_url(args)
    elif args.overload:
        record = _bench_overload(args)
    else:
        record = _bench_engine(args)
    line = json.dumps(record)
    print(line, flush=True)
    with open(args.out, "w") as f:
        f.write(line + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
