"""Interactive CLI client for the text-generation server.

TPU-native port of /root/reference/tools/text_generation_cli.py: reads
prompts from stdin, PUTs them to <url>/api, prints the generated text.
"""
from __future__ import annotations

import json
import sys
import urllib.error
import urllib.request


def main():
    if len(sys.argv) != 2:
        print("usage: text_generation_cli.py <host:port>")
        return 1
    url = f"http://{sys.argv[1]}/api"
    while True:
        try:
            prompt = input("Enter prompt: ")
        except EOFError:
            return 0
        n = input("Enter number of tokens to generate: ")
        payload = json.dumps({"prompts": [prompt],
                              "tokens_to_generate": int(n)}).encode()
        req = urllib.request.Request(
            url, data=payload, method="PUT",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req) as resp:
                data = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            # the server answers real statuses (400 bad request, 429
            # queue full, 500) with a JSON message — print, don't crash
            try:
                msg = json.loads(e.read()).get("message", str(e))
            except Exception:
                msg = str(e)
            print(f"Server error ({e.code}): {msg}")
            continue
        print("Megatron Response:")
        print(data["text"][0])


if __name__ == "__main__":
    sys.exit(main())
