#!/usr/bin/env bash
# Tokenize a train/valid pair of loose-JSON corpora into the indexed
# .bin/.idx format — the reference's tokenize-utils/entrypoint.sh flow
# (ref: tokenize-utils/entrypoint.sh) without the docker wrapper: this
# package needs no install step. See docs/tokenization.md.
#
#   tools/tokenize_corpus.sh TRAIN.jsonl VALID.jsonl OUT_PREFIX \
#       [TOKENIZER_TYPE] [TOKENIZER_MODEL_OR_VOCAB...]
#
# Defaults mirror the reference's Falcon example (HF tokenizer).
set -euo pipefail
# no cd: the caller's relative paths (corpora, vocab files, OUT_PREFIX)
# must resolve from the caller's directory; invoke the tool by its
# absolute path instead
tool="$(cd "$(dirname "$0")" && pwd)/preprocess_data.py"

train=${1:?usage: tokenize_corpus.sh TRAIN.jsonl VALID.jsonl OUT_PREFIX [type] [model...]}
valid=${2:?need VALID.jsonl}
prefix=${3:?need OUT_PREFIX}
ttype=${4:-HFTokenizer}
shift $(( $# > 4 ? 4 : $# ))

echo "Tokenizing ${train} -> ${prefix}-train"
python "${tool}" --input "${train}" \
    --output_prefix "${prefix}-train" --tokenizer_type "${ttype}" \
    --workers "${WORKERS:-2}" --append_eod "$@"

echo "Tokenizing ${valid} -> ${prefix}-valid"
python "${tool}" --input "${valid}" \
    --output_prefix "${prefix}-valid" --tokenizer_type "${ttype}" \
    --workers "${WORKERS:-2}" --append_eod "$@"
