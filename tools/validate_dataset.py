"""Offline corpus checker for `.idx`/`.bin` indexed-dataset pairs.

Runs the same validation MMapIndexedDataset performs at open — header
magic/version/dtype code, index size arithmetic vs the actual file
bytes, every pointer/size against the actual `.bin` size, doc_idx
bounds + monotonicity — WITHOUT starting a training job, so a corrupt
corpus is caught at submit time instead of 30 hours into a run.
Exit code is nonzero when any prefix fails, so it drops straight into
CI / preflight scripts:

  python tools/validate_dataset.py /data/corpus_a /data/corpus_b

Extra (advisory) findings beyond the open-time checks: trailing bytes
in `.bin` past the last pointed-to sequence, and a doc_idx whose first/
last entries don't bracket the sequence table.

`--smoke` (bench extras / CI): builds a tiny corpus in a tempdir,
verifies it validates clean, injects each dataset fault from
`FaultInjector.corrupt_dataset` (truncated `.bin`, garbage `.idx`,
out-of-range pointer) into copies, and proves every one is detected
with a typed `DatasetCorruptionError`. Emits ONE BENCH-style JSON
record, like chaos_train.py, so a validation regression surfaces in
the `BENCH_*.json` extras.

  JAX_PLATFORMS=cpu python tools/validate_dataset.py --smoke [--out F]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check_prefix(prefix: str) -> list:
    """-> list of problem strings (empty = valid). The authoritative
    checks live in MMapIndexedDataset.__init__ (open == validate);
    this adds advisory findings a lenient open tolerates."""
    from megatron_tpu.data.indexed_dataset import (DatasetCorruptionError,
                                                   data_file_path,
                                                   index_file_path)
    from megatron_tpu.data.indexed_dataset import MMapIndexedDataset

    problems = []
    for p in (index_file_path(prefix), data_file_path(prefix)):
        if not os.path.exists(p):
            problems.append(f"missing file: {p}")
    if problems:
        return problems
    try:
        ds = MMapIndexedDataset(prefix)
    except DatasetCorruptionError as e:
        return [str(e)]
    # advisory: bytes in .bin past the last sequence (harmless to train
    # on, but usually a sign of a mismatched .idx/.bin pair)
    bin_size = os.path.getsize(data_file_path(prefix))
    used = 0
    chunk = 1 << 22  # blockwise: no O(len) int64 temporaries
    for lo in range(0, len(ds), chunk):
        ends = (ds._pointers[lo:lo + chunk]
                + ds.sizes[lo:lo + chunk].astype("int64")
                * ds.dtype.itemsize)
        used = max(used, int(ends.max()))
    if bin_size > used:
        problems.append(
            f"advisory: {bin_size - used} trailing bytes in .bin past "
            "the last indexed sequence (mismatched pair?)")
    if len(ds.doc_idx):
        if int(ds.doc_idx[0]) != 0:
            problems.append(
                f"advisory: doc_idx starts at {int(ds.doc_idx[0])}, "
                "expected 0")
        if int(ds.doc_idx[-1]) != len(ds):
            problems.append(
                f"advisory: doc_idx ends at {int(ds.doc_idx[-1])}, "
                f"expected num_sequences={len(ds)}")
    return problems


def validate(prefixes: list, strict_advisory: bool = False) -> int:
    bad = 0
    for prefix in prefixes:
        problems = check_prefix(prefix)
        hard = [p for p in problems if not p.startswith("advisory:")]
        fail = hard or (strict_advisory and problems)
        status = "CORRUPT" if fail else "OK"
        print(f"{status}: {prefix}")
        for p in problems:
            print(f"  - {p}")
        bad += bool(fail)
    return bad


def run_smoke(workdir: str) -> dict:
    """Build → corrupt → detect, for every injectable dataset fault."""
    from megatron_tpu.data.indexed_dataset import IndexedDatasetBuilder
    from megatron_tpu.resilience.faults import FaultInjector

    clean = os.path.join(workdir, "clean")
    b = IndexedDatasetBuilder(clean, dtype="int32")
    for i in range(16):
        b.add_item(list(range(i, i + 12)))
        b.end_document()
    b.finalize()
    t0 = time.monotonic()
    clean_ok = not check_prefix(clean)

    # the corrupt→detect loop is the SAME drill chaos_train runs
    # post-chaos — one implementation, two records
    detected = FaultInjector.dataset_corruption_drill(workdir)
    wall_s = time.monotonic() - t0
    ok = clean_ok and all(detected.values())
    return {
        "metric": "dataset_validation_smoke",
        "value": sum(detected.values()),
        "unit": f"faults detected of {len(detected)} injected",
        "vs_baseline": None,
        "completed": ok,
        "clean_validates": clean_ok,
        "detected": detected,
        "wall_s": round(wall_s, 3),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("prefixes", nargs="*",
                    help="dataset prefixes (PATH for PATH.idx/PATH.bin)")
    ap.add_argument("--smoke", action="store_true",
                    help="self-test: inject every dataset fault into a "
                         "tiny corpus, prove each is detected")
    ap.add_argument("--strict_advisory", action="store_true",
                    help="advisory findings also fail the check")
    ap.add_argument("--out", type=str, default=None,
                    help="(--smoke) also write the JSON record here")
    args = ap.parse_args(argv)

    if args.smoke:
        workdir = tempfile.mkdtemp(prefix="validate_dataset_")
        try:
            record = run_smoke(workdir)
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
        line = json.dumps(record)
        print(line, flush=True)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0 if record["completed"] else 1

    if not args.prefixes:
        ap.error("give at least one dataset prefix (or --smoke)")
    bad = validate(args.prefixes, strict_advisory=args.strict_advisory)
    if bad:
        print(f"{bad}/{len(args.prefixes)} prefixes corrupt", flush=True)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
