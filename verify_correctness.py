"""Correctness gate: megatron_tpu vs the HuggingFace reference implementation.

TPU-native equivalent of the reference's verify_correctness.py
(ref: /root/reference/verify_correctness.py:107-194), which runs the Megatron
model and a trusted baseline (HF/Meta) on identical batches and reports the
max-abs logit error and loss delta, with the CI tolerance avg-max-abs <= 1e-3
in fp32 (ref: tests/test_llama_weights.py:106).

Usage:
  python verify_correctness.py --hf_path <dir-or-name> --model_size 7b
  python verify_correctness.py --synthetic          # no weights needed:
      builds a small random HF Llama, converts it, compares logits.

The synthetic mode makes the gate hermetic (no multi-GB downloads) while
exercising exactly the same conversion + numerics path as real weights.
"""
from __future__ import annotations

import argparse
import dataclasses
import sys

import numpy as np

from megatron_tpu.utils.platform import ensure_env_platform
ensure_env_platform()


def compare_llama(hf_model, cfg, tokens: np.ndarray,
                  family: str = "llama") -> dict:
    """Run HF (torch, fp32) and megatron_tpu (jax, fp32) on `tokens`.

    Returns {max_abs_err, avg_max_abs_err, loss_hf, loss_ours}
    (ref: verify_correctness.py:143-194 reports the same quantities).
    `family` picks the converter: "llama" or "mixtral" (MoE)."""
    import jax
    import jax.numpy as jnp
    import torch

    from megatron_tpu.convert import (hf_llama_to_params,
                                      hf_mixtral_to_params)
    from megatron_tpu.models import language_model as lm
    from megatron_tpu.ops.cross_entropy import cross_entropy_loss

    cfg = dataclasses.replace(cfg, compute_dtype="float32")
    sd = {k: v.detach().cpu().numpy() for k, v in hf_model.state_dict().items()}
    conv = {"llama": hf_llama_to_params,
            "mixtral": hf_mixtral_to_params}[family]
    params = conv(sd, cfg)

    with torch.no_grad():
        out = hf_model(torch.tensor(tokens)).logits.float().numpy()

    logits, _ = lm.model_forward(
        params, jnp.asarray(tokens), cfg, logits_dtype=jnp.float32)
    ours = np.asarray(logits)[..., :cfg.vocab_size]

    abs_err = np.abs(ours - out)
    labels = tokens[:, 1:]
    loss_ours = float(np.mean(np.asarray(cross_entropy_loss(
        jnp.asarray(ours[:, :-1]), jnp.asarray(labels),
        vocab_size=cfg.vocab_size))))
    lp = torch.nn.functional.cross_entropy(
        torch.tensor(out[:, :-1]).reshape(-1, out.shape[-1]),
        torch.tensor(labels).reshape(-1).long())
    return {
        "max_abs_err": float(abs_err.max()),
        "avg_max_abs_err": float(abs_err.max(axis=-1).mean()),
        "loss_ours": loss_ours,
        "loss_hf": float(lp),
    }


def make_synthetic_hf_llama(vocab=128, hidden=64, layers=4, heads=4, kv=2,
                            ffn=176, seq=64, seed=0):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(seed)
    hf_cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=hidden, num_hidden_layers=layers,
        num_attention_heads=heads, num_key_value_heads=kv,
        intermediate_size=ffn, max_position_embeddings=seq,
        rms_norm_eps=1e-5, tie_word_embeddings=False,
        attention_bias=False, mlp_bias=False)
    model = LlamaForCausalLM(hf_cfg).eval()
    from megatron_tpu.config import ModelConfig
    cfg = ModelConfig(
        num_layers=layers, hidden_size=hidden, num_attention_heads=heads,
        num_kv_heads=kv, ffn_hidden_size=ffn, vocab_size=vocab,
        make_vocab_size_divisible_by=1, seq_length=seq,
        activation="swiglu", norm_type="rmsnorm", use_rotary_emb=True,
        use_bias=False, tie_embed_logits=False,
        compute_dtype="float32").derived()
    return model, cfg


def make_synthetic_hf_mixtral(vocab=160, hidden=64, layers=2, heads=4, kv=2,
                              ffn=96, experts=4, top_k=2, seq=64, seed=0):
    """Random tiny HF Mixtral + the matching MoE ModelConfig — extends the
    hermetic gate to the MoE conversion path (capacity E/K => dropless,
    so parity is exact, not capacity-truncated)."""
    import torch
    from transformers import MixtralConfig, MixtralForCausalLM

    from megatron_tpu.config import mixtral_config
    torch.manual_seed(seed)
    model = MixtralForCausalLM(MixtralConfig(
        vocab_size=vocab, hidden_size=hidden, num_hidden_layers=layers,
        num_attention_heads=heads, num_key_value_heads=kv,
        intermediate_size=ffn, num_local_experts=experts,
        num_experts_per_tok=top_k, max_position_embeddings=seq,
        rope_theta=1e6, rms_norm_eps=1e-5,
        tie_word_embeddings=False)).eval()
    cfg = mixtral_config(
        "tiny", num_layers=layers, hidden_size=hidden,
        num_attention_heads=heads, num_kv_heads=kv, ffn_hidden_size=ffn,
        vocab_size=vocab, seq_length=seq, num_experts=experts,
        moe_top_k=top_k, make_vocab_size_divisible_by=1,
        compute_dtype="float32")
    return model, cfg


def seed_hf_llama_numpy(model, seed=0):
    """Overwrite every parameter with numpy-seeded values. torch's RNG
    stream (manual_seed) is not guaranteed stable across torch versions;
    np.random.Generator(PCG64) is a pinned algorithm, so models seeded
    this way regenerate bit-identically forever — the property the
    golden-logit fixture (--save_golden / --golden) depends on."""
    import torch
    rng = np.random.default_rng(seed)
    new = {}
    for k, v in model.state_dict().items():
        if k.endswith("norm.weight"):  # RMSNorm gains start at ~1
            arr = 1.0 + 0.02 * rng.standard_normal(tuple(v.shape))
        else:
            arr = 0.02 * rng.standard_normal(tuple(v.shape))
        new[k] = torch.tensor(arr.astype(np.float32))
    model.load_state_dict(new)
    return model


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--hf_path", type=str, default=None)
    p.add_argument("--model_size", type=str, default=None,
                   help="preset name; defaults to '7b' (llama) or "
                        "'8x7b' (mixtral) per --family")
    p.add_argument("--family", type=str, default="llama",
                   choices=["llama", "mixtral"])
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--tolerance", type=float, default=1e-3)
    # Golden-logit fixture mode (VERDICT r3 item 5): real Llama weights
    # are unreachable from this environment (zero egress — the blocked
    # command is documented in COVERAGE.md), so the numerics gate is
    # pinned instead: --save_golden writes the numpy-seeded synthetic
    # model's fp32 logits; --golden replays conversion+forward and
    # compares against the pinned values at the same <=1e-3 avg-max-abs
    # the reference CI uses on real weights.
    p.add_argument("--save_golden", type=str, default=None)
    p.add_argument("--golden", type=str, default=None)
    args = p.parse_args(argv)
    if args.model_size is None:
        args.model_size = "8x7b" if args.family == "mixtral" else "7b"

    if args.save_golden or args.golden:
        return golden_mode(args)

    if args.synthetic or args.hf_path is None:
        if args.family == "mixtral":
            model, cfg = make_synthetic_hf_mixtral(seq=args.seq)
        else:
            model, cfg = make_synthetic_hf_llama(seq=args.seq)
    else:
        from transformers import AutoModelForCausalLM

        from megatron_tpu.config import llama2_config, mixtral_config
        model = AutoModelForCausalLM.from_pretrained(
            args.hf_path, torch_dtype="float32").eval()
        cfg = (mixtral_config(args.model_size, compute_dtype="float32")
               if args.family == "mixtral"
               else llama2_config(args.model_size, compute_dtype="float32"))

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size,
                          (args.batch, args.seq)).astype(np.int32)
    r = compare_llama(model, cfg, tokens, family=args.family)
    print(f"max abs logit error:     {r['max_abs_err']:.2e}")
    print(f"avg max-abs logit error: {r['avg_max_abs_err']:.2e}")
    print(f"loss ours / hf:          {r['loss_ours']:.6f} / {r['loss_hf']:.6f}")
    ok = r["avg_max_abs_err"] <= args.tolerance
    print("PASS" if ok else "FAIL",
          f"(tolerance {args.tolerance:.0e}, "
          f"ref gate: tests/test_llama_weights.py:106)")
    return 0 if ok else 1


def golden_mode(args) -> int:
    """Create or check the pinned-logit fixture (hermetic real-weight-gate
    stand-in; see the --save_golden/--golden help above)."""
    import jax.numpy as jnp

    from megatron_tpu.convert import hf_llama_to_params
    from megatron_tpu.models import language_model as lm

    model, cfg = make_synthetic_hf_llama(seq=args.seq)
    seed_hf_llama_numpy(model, seed=0)
    cfg = dataclasses.replace(cfg, compute_dtype="float32")
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size,
                          (args.batch, args.seq)).astype(np.int32)
    sd = {k: v.detach().cpu().numpy()
          for k, v in model.state_dict().items()}
    params = hf_llama_to_params(sd, cfg)
    logits, _ = lm.model_forward(params, jnp.asarray(tokens), cfg,
                                 logits_dtype=jnp.float32)
    ours = np.asarray(logits)[..., :cfg.vocab_size]

    if args.save_golden:
        np.savez_compressed(args.save_golden, tokens=tokens, logits=ours)
        print(f"golden fixture written: {args.save_golden} "
              f"(tokens {tokens.shape}, logits {ours.shape})")
        return 0
    pinned = np.load(args.golden)
    assert np.array_equal(pinned["tokens"], tokens), (
        "fixture tokens differ — np.random.Generator stream changed?")
    avg_max_abs = float(np.abs(ours - pinned["logits"]).max(-1).mean())
    ok = avg_max_abs <= args.tolerance
    print(f"avg max-abs vs golden: {avg_max_abs:.2e} "
          f"({'PASS' if ok else 'FAIL'}, tolerance {args.tolerance:.0e})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
