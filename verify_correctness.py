"""Correctness gate: megatron_tpu vs the HuggingFace reference implementation.

TPU-native equivalent of the reference's verify_correctness.py
(ref: /root/reference/verify_correctness.py:107-194), which runs the Megatron
model and a trusted baseline (HF/Meta) on identical batches and reports the
max-abs logit error and loss delta, with the CI tolerance avg-max-abs <= 1e-3
in fp32 (ref: tests/test_llama_weights.py:106).

Usage:
  python verify_correctness.py --hf_path <dir-or-name> --model_size 7b
  python verify_correctness.py --synthetic          # no weights needed:
      builds a small random HF Llama, converts it, compares logits.

The synthetic mode makes the gate hermetic (no multi-GB downloads) while
exercising exactly the same conversion + numerics path as real weights.
"""
from __future__ import annotations

import argparse
import dataclasses
import sys

import numpy as np

from megatron_tpu.utils.platform import ensure_env_platform
ensure_env_platform()


def compare_llama(hf_model, cfg, tokens: np.ndarray,
                  family: str = "llama") -> dict:
    """Run HF (torch, fp32) and megatron_tpu (jax, fp32) on `tokens`.

    Returns {max_abs_err, avg_max_abs_err, loss_hf, loss_ours}
    (ref: verify_correctness.py:143-194 reports the same quantities).
    `family` picks the converter: "llama" or "mixtral" (MoE)."""
    import jax
    import jax.numpy as jnp
    import torch

    from megatron_tpu.convert import (hf_llama_to_params,
                                      hf_mixtral_to_params)
    from megatron_tpu.models import language_model as lm
    from megatron_tpu.ops.cross_entropy import cross_entropy_loss

    cfg = dataclasses.replace(cfg, compute_dtype="float32")
    sd = {k: v.detach().cpu().numpy() for k, v in hf_model.state_dict().items()}
    conv = {"llama": hf_llama_to_params,
            "mixtral": hf_mixtral_to_params}[family]
    params = conv(sd, cfg)

    with torch.no_grad():
        out = hf_model(torch.tensor(tokens)).logits.float().numpy()

    logits, _ = lm.model_forward(
        params, jnp.asarray(tokens), cfg, logits_dtype=jnp.float32)
    ours = np.asarray(logits)[..., :cfg.vocab_size]

    abs_err = np.abs(ours - out)
    labels = tokens[:, 1:]
    loss_ours = float(np.mean(np.asarray(cross_entropy_loss(
        jnp.asarray(ours[:, :-1]), jnp.asarray(labels),
        vocab_size=cfg.vocab_size))))
    lp = torch.nn.functional.cross_entropy(
        torch.tensor(out[:, :-1]).reshape(-1, out.shape[-1]),
        torch.tensor(labels).reshape(-1).long())
    return {
        "max_abs_err": float(abs_err.max()),
        "avg_max_abs_err": float(abs_err.max(axis=-1).mean()),
        "loss_ours": loss_ours,
        "loss_hf": float(lp),
    }


def make_synthetic_hf_llama(vocab=128, hidden=64, layers=4, heads=4, kv=2,
                            ffn=176, seq=64, seed=0):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(seed)
    hf_cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=hidden, num_hidden_layers=layers,
        num_attention_heads=heads, num_key_value_heads=kv,
        intermediate_size=ffn, max_position_embeddings=seq,
        rms_norm_eps=1e-5, tie_word_embeddings=False,
        attention_bias=False, mlp_bias=False)
    model = LlamaForCausalLM(hf_cfg).eval()
    from megatron_tpu.config import ModelConfig
    cfg = ModelConfig(
        num_layers=layers, hidden_size=hidden, num_attention_heads=heads,
        num_kv_heads=kv, ffn_hidden_size=ffn, vocab_size=vocab,
        make_vocab_size_divisible_by=1, seq_length=seq,
        activation="swiglu", norm_type="rmsnorm", use_rotary_emb=True,
        use_bias=False, tie_embed_logits=False,
        compute_dtype="float32").derived()
    return model, cfg


def make_synthetic_hf_mixtral(vocab=160, hidden=64, layers=2, heads=4, kv=2,
                              ffn=96, experts=4, top_k=2, seq=64, seed=0):
    """Random tiny HF Mixtral + the matching MoE ModelConfig — extends the
    hermetic gate to the MoE conversion path (capacity E/K => dropless,
    so parity is exact, not capacity-truncated)."""
    import torch
    from transformers import MixtralConfig, MixtralForCausalLM

    from megatron_tpu.config import mixtral_config
    torch.manual_seed(seed)
    model = MixtralForCausalLM(MixtralConfig(
        vocab_size=vocab, hidden_size=hidden, num_hidden_layers=layers,
        num_attention_heads=heads, num_key_value_heads=kv,
        intermediate_size=ffn, num_local_experts=experts,
        num_experts_per_tok=top_k, max_position_embeddings=seq,
        rope_theta=1e6, rms_norm_eps=1e-5,
        tie_word_embeddings=False)).eval()
    cfg = mixtral_config(
        "tiny", num_layers=layers, hidden_size=hidden,
        num_attention_heads=heads, num_kv_heads=kv, ffn_hidden_size=ffn,
        vocab_size=vocab, seq_length=seq, num_experts=experts,
        moe_top_k=top_k, make_vocab_size_divisible_by=1,
        compute_dtype="float32")
    return model, cfg


def seed_hf_llama_numpy(model, seed=0):
    """Overwrite every parameter with numpy-seeded values. torch's RNG
    stream (manual_seed) is not guaranteed stable across torch versions;
    np.random.Generator(PCG64) is a pinned algorithm, so models seeded
    this way regenerate bit-identically forever — the property the
    golden-logit fixture (--save_golden / --golden) depends on."""
    import torch
    rng = np.random.default_rng(seed)
    new = {}
    for k, v in model.state_dict().items():
        if k.endswith("norm.weight"):  # RMSNorm gains start at ~1
            arr = 1.0 + 0.02 * rng.standard_normal(tuple(v.shape))
        else:
            arr = 0.02 * rng.standard_normal(tuple(v.shape))
        new[k] = torch.tensor(arr.astype(np.float32))
    model.load_state_dict(new)
    return model


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--hf_path", type=str, default=None)
    p.add_argument("--model_size", type=str, default=None,
                   help="preset name; defaults to '7b' (llama) or "
                        "'8x7b' (mixtral) per --family")
    p.add_argument("--family", type=str, default="llama",
                   choices=["llama", "mixtral"])
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--tolerance", type=float, default=1e-3)
    # Golden-logit fixture mode (VERDICT r3 item 5): real Llama weights
    # are unreachable from this environment (zero egress — the blocked
    # command is documented in COVERAGE.md), so the numerics gate is
    # pinned instead: --save_golden writes the numpy-seeded synthetic
    # model's fp32 logits; --golden replays conversion+forward and
    # compares against the pinned values at the same <=1e-3 avg-max-abs
    # the reference CI uses on real weights.
    p.add_argument("--save_golden", type=str, default=None)
    p.add_argument("--golden", type=str, default=None)
    # Loss-trajectory fixture mode (VERDICT r4 next #3): pins an N-step
    # training trajectory — losses, lr schedule, grad norms, and the
    # fp16 scaler's exact scale/skip sequence — on the numpy-seeded
    # synthetic model, turning optimizer/scheduler/scaler semantics into
    # a hermetic regression gate (the strongest loss-curve-match posture
    # available without egress; ref: megatron/optimizer/optimizer.py:
    # 407-466 step semantics, megatron/training.py:452-626 train loop).
    p.add_argument("--save_loss_trajectory", type=str, default=None)
    p.add_argument("--loss_trajectory", type=str, default=None)
    p.add_argument("--trajectory_steps", type=int, default=100)
    args = p.parse_args(argv)

    if args.save_loss_trajectory or args.loss_trajectory:
        return trajectory_mode(args)
    if args.model_size is None:
        args.model_size = "8x7b" if args.family == "mixtral" else "7b"

    if args.save_golden or args.golden:
        return golden_mode(args)

    if args.synthetic or args.hf_path is None:
        if args.family == "mixtral":
            model, cfg = make_synthetic_hf_mixtral(seq=args.seq)
        else:
            model, cfg = make_synthetic_hf_llama(seq=args.seq)
    else:
        from transformers import AutoModelForCausalLM

        from megatron_tpu.config import llama2_config, mixtral_config
        model = AutoModelForCausalLM.from_pretrained(
            args.hf_path, torch_dtype="float32").eval()
        cfg = (mixtral_config(args.model_size, compute_dtype="float32")
               if args.family == "mixtral"
               else llama2_config(args.model_size, compute_dtype="float32"))

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size,
                          (args.batch, args.seq)).astype(np.int32)
    r = compare_llama(model, cfg, tokens, family=args.family)
    print(f"max abs logit error:     {r['max_abs_err']:.2e}")
    print(f"avg max-abs logit error: {r['avg_max_abs_err']:.2e}")
    print(f"loss ours / hf:          {r['loss_ours']:.6f} / {r['loss_hf']:.6f}")
    ok = r["avg_max_abs_err"] <= args.tolerance
    print("PASS" if ok else "FAIL",
          f"(tolerance {args.tolerance:.0e}, "
          f"ref gate: tests/test_llama_weights.py:106)")
    return 0 if ok else 1


def golden_mode(args) -> int:
    """Create or check the pinned-logit fixture (hermetic real-weight-gate
    stand-in; see the --save_golden/--golden help above)."""
    import jax.numpy as jnp

    from megatron_tpu.convert import hf_llama_to_params
    from megatron_tpu.models import language_model as lm

    model, cfg = make_synthetic_hf_llama(seq=args.seq)
    seed_hf_llama_numpy(model, seed=0)
    cfg = dataclasses.replace(cfg, compute_dtype="float32")
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size,
                          (args.batch, args.seq)).astype(np.int32)
    sd = {k: v.detach().cpu().numpy()
          for k, v in model.state_dict().items()}
    params = hf_llama_to_params(sd, cfg)
    logits, _ = lm.model_forward(params, jnp.asarray(tokens), cfg,
                                 logits_dtype=jnp.float32)
    ours = np.asarray(logits)[..., :cfg.vocab_size]

    if args.save_golden:
        np.savez_compressed(args.save_golden, tokens=tokens, logits=ours)
        print(f"golden fixture written: {args.save_golden} "
              f"(tokens {tokens.shape}, logits {ours.shape})")
        return 0
    pinned = np.load(args.golden)
    assert np.array_equal(pinned["tokens"], tokens), (
        "fixture tokens differ — np.random.Generator stream changed?")
    avg_max_abs = float(np.abs(ours - pinned["logits"]).max(-1).mean())
    ok = avg_max_abs <= args.tolerance
    print(f"avg max-abs vs golden: {avg_max_abs:.2e} "
          f"({'PASS' if ok else 'FAIL'}, tolerance {args.tolerance:.0e})")
    return 0 if ok else 1


def run_loss_trajectory(steps: int = 100, mode: str = "fp32") -> dict:
    """Run `steps` full train steps (adam + clip + warmup-cosine lr + wd
    + dynamic fp16 scaler) on the numpy-seeded synthetic Llama.

    mode "fp32": float32 compute — pins optimizer/scheduler math tightly.
    mode "fp16": float16 compute with a deliberately-overflowing initial
    loss scale — the first steps MUST overflow and back off (hysteresis
    then halving), later windows MUST grow the scale back; the exact
    scale/skip sequence is the pinned artifact (discrete powers of two —
    immune to float jitter). Ref: megatron/optimizer/grad_scaler.py:
    75-120, optimizer.py:407-466.

    Returns {losses, lr, grad_norm, loss_scale, found_inf} as np arrays
    of length `steps`. CPU-only for hermeticity (the fixture is created
    and checked on the same backend the test tier runs on)."""
    import jax
    import jax.numpy as jnp

    from megatron_tpu.config import (MegatronConfig, OptimizerConfig,
                                     ParallelConfig, TrainingConfig)
    from megatron_tpu.convert import hf_llama_to_params
    from megatron_tpu.parallel.mesh import build_mesh
    from megatron_tpu.training import make_train_step
    from megatron_tpu.training.train_step import state_from_params

    assert jax.default_backend() == "cpu", (
        "loss-trajectory fixtures are CPU-pinned; run under "
        "JAX_PLATFORMS=cpu (jax.config.update('jax_platforms','cpu') "
        "before any device touch)")
    model, mcfg = make_synthetic_hf_llama(seq=64)
    seed_hf_llama_numpy(model, seed=0)
    mcfg = dataclasses.replace(
        mcfg, compute_dtype="float32" if mode == "fp32" else "float16")
    cfg = MegatronConfig(
        model=mcfg,
        parallel=ParallelConfig(),
        optimizer=OptimizerConfig(
            lr=3e-3, min_lr=3e-4, lr_decay_style="cosine",
            lr_decay_iters=steps, lr_warmup_iters=10,
            weight_decay=0.1, clip_grad=1.0,
            # fp16: start ABOVE the fp16 max so the automaton must
            # back off (hysteresis first), then re-grow within the run
            initial_loss_scale=2.0 ** 24, loss_scale_window=25,
            hysteresis=2),
        training=TrainingConfig(micro_batch_size=2, global_batch_size=2,
                                train_iters=steps),
    ).validate(n_devices=1)
    sd = {k: v.detach().cpu().numpy()
          for k, v in model.state_dict().items()}
    params = hf_llama_to_params(sd, cfg.model)
    params = jax.tree.map(jnp.asarray, params)
    state = state_from_params(params, cfg)
    mesh = build_mesh(cfg.parallel, devices=jax.devices()[:1])
    step = make_train_step(cfg, mesh=mesh, donate=False)

    # a fixed 4-batch cycle: unlearnable fresh-random tokens would keep
    # the loss pinned at ln(V) and the trajectory would gate nothing —
    # cycling lets adam genuinely descend (memorization), so optimizer
    # regressions show up as a DIFFERENT curve, not a flat one
    data_rng = np.random.default_rng(1)
    cycle = [data_rng.integers(0, cfg.model.vocab_size,
                               (1, 2, 65)).astype(np.int32)
             for _ in range(4)]
    out = {k: [] for k in ("losses", "lr", "grad_norm", "loss_scale",
                           "found_inf")}
    for i in range(steps):
        batch = {"tokens": jnp.asarray(cycle[i % 4]),
                 "loss_mask": jnp.ones((1, 2, 64), jnp.float32)}
        state, m = step(state, batch, jax.random.PRNGKey(i))
        out["losses"].append(float(m["lm_loss"]))
        out["lr"].append(float(m["lr"]))
        out["grad_norm"].append(float(m["grad_norm"]))
        out["loss_scale"].append(float(m["loss_scale"]))
        out["found_inf"].append(float(m["found_inf"]))
    return {k: np.asarray(v) for k, v in out.items()}


def trajectory_mode(args) -> int:
    """Create or check the pinned N-step loss-trajectory fixture."""
    steps = args.trajectory_steps
    got = {mode: run_loss_trajectory(steps, mode)
           for mode in ("fp32", "fp16")}
    if args.save_loss_trajectory:
        flat = {f"{mode}_{k}": v for mode, d in got.items()
                for k, v in d.items()}
        np.savez_compressed(args.save_loss_trajectory, steps=steps, **flat)
        print(f"trajectory fixture written: {args.save_loss_trajectory} "
              f"({steps} steps x {len(flat)} series)")
        fp16 = got["fp16"]
        print(f"  fp32 loss {got['fp32']['losses'][0]:.4f} -> "
              f"{got['fp32']['losses'][-1]:.4f}; fp16 skips="
              f"{int(fp16['found_inf'].sum())} final scale="
              f"{fp16['loss_scale'][-1]:.0f}")
        return 0
    pinned = np.load(args.loss_trajectory)
    assert int(pinned["steps"]) == steps, (
        f"fixture has {int(pinned['steps'])} steps, ran {steps}")
    failures = []

    def check(name, a, b, rtol, atol=0.0, exact=False):
        ok = (np.array_equal(a, b) if exact
              else np.allclose(a, b, rtol=rtol, atol=atol))
        worst = float(np.max(np.abs(a - b))) if len(a) else 0.0
        print(f"  {name:<18} {'PASS' if ok else 'FAIL'} "
              f"(max abs dev {worst:.3e}{', exact' if exact else ''})")
        if not ok:
            failures.append(name)

    print("fp32 trajectory (optimizer/scheduler math):")
    f32 = got["fp32"]
    check("losses", f32["losses"], pinned["fp32_losses"], rtol=2e-4,
          atol=1e-5)
    check("lr", f32["lr"], pinned["fp32_lr"], rtol=1e-6)
    check("grad_norm", f32["grad_norm"], pinned["fp32_grad_norm"],
          rtol=1e-3, atol=1e-5)
    print("fp16 trajectory (scaler automaton):")
    f16 = got["fp16"]
    check("loss_scale", f16["loss_scale"], pinned["fp16_loss_scale"],
          rtol=0, exact=True)
    check("found_inf", f16["found_inf"], pinned["fp16_found_inf"],
          rtol=0, exact=True)
    # fp16 losses jitter more; gate finiteness + coarse agreement on the
    # applied (non-skipped) steps
    applied = pinned["fp16_found_inf"] == 0
    check("losses(applied)", f16["losses"][applied],
          pinned["fp16_losses"][applied], rtol=1e-2, atol=1e-3)
    print("PASS" if not failures else f"FAIL: {failures}")
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
